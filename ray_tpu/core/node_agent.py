"""Node agent — the per-node scheduler, worker pool, and object-store host.

TPU-native analog of the reference's raylet (/root/reference/src/ray/raylet/ —
NodeManager node_manager.h:120): grants worker leases
(HandleRequestWorkerLease node_manager.cc:1627; queueing mirrors
ClusterLeaseManager::QueueAndScheduleLease), spawns/monitors worker processes
(worker_pool.h PopWorker/StartWorkerProcess), hosts the shared-memory object
store in-process (store_runner.cc runs plasma inside the raylet), reserves
placement-group bundles with 2-phase prepare/commit
(placement_group_resource_manager.cc), spills leases back to other nodes
(hybrid policy), and releases a blocked worker's CPU so nested tasks can't
deadlock the pool (the reference's blocked-worker resource release).

TPU-first: if the node hosts TPU chips, the agent pins ONE worker process per
chip group and routes all TPU-resource leases to it — chips admit a single
attached process (SURVEY.md §7 hard-part 7), unlike the fungible CPU pool.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, NodeID, PlacementGroupID, WorkerID
from ray_tpu.core.object_store import make_store
from ray_tpu.core.rpc import ClientPool, RpcServer
from ray_tpu.core.scheduler import add, fits, subtract
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

# Built-in node-agent metrics (ISSUE 4; ref: stats/metric_defs.cc
# raylet-side series). Shipped to the CP by the per-process MetricsFlusher.
_SPILLBACK_COUNTER = _metrics.Counter(
    "ray_tpu_scheduler_spillbacks_total",
    "lease requests redirected to another node (hybrid spillback)")
_STORE_BYTES_STORED = _metrics.Counter(
    "ray_tpu_object_store_bytes_stored_total",
    "bytes allocated in this node's shared-memory store")
_STORE_HITS = _metrics.Counter(
    "ray_tpu_object_store_hits_total",
    "object lookups served from the local store")
_STORE_MISSES = _metrics.Counter(
    "ray_tpu_object_store_misses_total",
    "object lookups that required a remote pull or failed locally")
_STORE_SPILLED_GAUGE = _metrics.Gauge(
    "ray_tpu_object_store_spilled_objects",
    "objects spilled to disk by this node's store")
_WORKER_COUNT_GAUGE = _metrics.Gauge(
    "ray_tpu_node_agent_workers",
    "worker processes in this agent's pool, by state",
    tag_keys=("state",))
_ENV_CACHE_GAUGE = _metrics.Gauge(
    "ray_tpu_node_agent_env_cache_entries",
    "materialized runtime-env cache entries on this node")


class _InProcHandle:
    """Process-like facade over an in-process WorkerRuntime, so the agent's
    monitor/kill/reap paths (poll/terminate/kill/wait/returncode) work
    unchanged for in-process workers — the fake_multi_node-style harness
    that lets scale and autoscaler tests run hundreds of workers as threads
    instead of processes (reference:
    python/ray/autoscaler/_private/fake_multi_node/node_provider.py)."""

    def __init__(self, rt):
        self._rt = rt
        self._exited = threading.Event()
        self.returncode: int | None = None

    def exit(self, code: int = 0) -> None:
        """Soft process-exit: bound to WorkerRuntime.on_exit."""
        if self._exited.is_set():
            return
        self.returncode = code
        self._exited.set()
        threading.Thread(target=self._shutdown, daemon=True).start()

    def _shutdown(self):
        try:
            self._rt.shutdown()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass

    # Popen facade ------------------------------------------------------
    def poll(self):
        return self.returncode if self._exited.is_set() else None

    def terminate(self):
        self.exit(-15)

    def kill(self):
        self.exit(-9)

    def wait(self, timeout: float | None = None):
        self._exited.wait(timeout)
        return self.returncode


@dataclass
class _WorkerInfo:
    worker_id: WorkerID
    addr: tuple[str, int] | None = None
    proc: subprocess.Popen | None = None
    pid: int = 0
    busy: bool = False
    actor_id: ActorID | None = None
    is_tpu_worker: bool = False
    env_key: str = ""  # runtime-env hash (worker pool keyed per env)
    idle_since: float = field(default_factory=time.monotonic)
    ready = None  # threading.Event
    log_paths: tuple[str, str] | None = None
    log_offsets: list = field(default_factory=lambda: [0, 0])
    job_id: str = ""  # hex of the job the current/last lease belongs to


@dataclass
class _Lease:
    lease_id: str
    worker_id: WorkerID
    resources: dict[str, float]
    pg_id: PlacementGroupID | None = None
    bundle_index: int = -1
    lessee: WorkerID | None = None  # holder; reclaimed if it dies


class NodeAgent:
    def __init__(self, cp_addr: tuple[str, int], *, host: str = "127.0.0.1", port: int = 0,
                 resources: dict[str, float] | None = None,
                 labels: dict[str, str] | None = None,
                 object_store_memory: int | None = None,
                 node_id: NodeID | None = None,
                 inproc_workers: bool = False):
        cfg = get_config()
        # in-process workers: WorkerRuntimes as threads instead of
        # subprocesses (see _InProcHandle) — the scale/autoscaler harness
        self._inproc_workers = bool(inproc_workers)
        self.node_id = node_id or NodeID.from_random()
        self.cp_addr = tuple(cp_addr)
        self._lock = threading.RLock()
        self._pool = ClientPool("agent")
        self._workers: dict[WorkerID, _WorkerInfo] = {}
        self._leases: dict[str, _Lease] = {}
        self._lease_cv = threading.Condition(self._lock)
        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
        self.resources_total = dict(resources)
        self.available = dict(resources)
        self.labels = dict(labels or {})
        self._detect_tpu_topology()
        # pg_id -> bundle_index -> remaining reserved resources
        self._pg_reserved: dict[PlacementGroupID, dict[int, dict[str, float]]] = {}
        self._pg_prepared: dict[PlacementGroupID, dict[int, dict[str, float]]] = {}
        self.store = make_store(object_store_memory or cfg.object_store_memory,
                                prefix=f"rtpu{os.getpid() % 10000}_{self.node_id.hex()[:6]}")
        self.store.on_evict = self._on_store_evict
        self._object_owners: dict = {}  # ObjectID -> owner addr, for evict notices
        self._pull_cv = threading.Condition()
        self._relay_channels: dict[str, object] = {}  # shadow path -> Channel
        self._channel_relay_stops: dict = {}  # (path, index) -> stop Event
        self._pull_inflight_bytes = 0
        self._pulls_in_progress: dict = {}  # ObjectID -> Event (single-flight)
        self._stopped = threading.Event()
        # graceful drain (ref: node_manager.proto:448 DrainRaylet): a
        # draining agent refuses new leases (redirecting where possible)
        # but lets in-flight ones finish; set by the CP's drain notify or
        # learned from the heartbeat reply's `state` field.
        self._draining = False
        self._res_version = 0  # versioned resource-view sync (RaySyncer)
        self._server = RpcServer(
            self._handle, host=host, port=port, name="nodeagent",
            blocking_methods={"lease_worker", "pull_object",
                              "wait_object_local", "channel_push",
                              "drain_objects"},
            pool_size=16)
        self.addr = self._server.addr
        self._register_with_cp()
        # per-process metrics auto-flush (ISSUE 4): delta snapshots to the
        # CP time-series store every metrics_flush_interval_s + once on
        # stop(). In-process harnesses share one flusher per process (first
        # component to start it wins; `stop_flusher` is owner-checked).
        self._metrics_flusher = None
        if cfg.metrics_enabled:
            # acknowledged call, not a one-way notify: a flush into a CP
            # that just died can land in the kernel buffer and vanish —
            # the reply makes the failure visible so the flusher's outage
            # backlog keeps the payload for re-send
            self._metrics_flusher = _metrics.start_flusher(
                lambda p: self._pool.get(self.cp_addr).call(
                    "metrics_report", p, timeout=10.0),
                source=f"node:{self.node_id.hex()}",
                node_id=self.node_id.hex())
        self._memory_monitor = None
        if cfg.memory_usage_threshold > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor
            self._memory_monitor = MemoryMonitor(
                self._oom_kill_worker, cfg.memory_usage_threshold,
                cfg.memory_monitor_interval_s)
        self._monitor_thread = threading.Thread(
            target=self._monitor_workers, name="agent-monitor", daemon=True)
        self._monitor_thread.start()
        if cfg.log_to_driver:
            threading.Thread(target=self._log_monitor_loop,
                             name="agent-logmon", daemon=True).start()

    def _detect_tpu_topology(self):
        """Populate TPU resources/labels from the environment (generalizes the
        reference's TPU accelerator manager, _private/accelerators/tpu.py:199,
        topology inference tpu.py:114)."""
        from ray_tpu.parallel.topology import detect_local_topology
        topo = detect_local_topology()
        if topo is None:
            return
        self.resources_total.setdefault("TPU", float(topo.chips_per_host))
        self.available.setdefault("TPU", float(topo.chips_per_host))
        self.labels.setdefault("slice_name", topo.slice_name)
        self.labels.setdefault("pod_type", topo.pod_type)
        self.labels.setdefault("topology", topo.topology)
        self.labels.setdefault("tpu_worker_id", str(topo.worker_id))

    def _register_with_cp(self):
        self._pool.get(self.cp_addr).call_with_retry(
            "register_node",
            {"node_id": self.node_id, "addr": self.addr,
             "resources": self.resources_total, "labels": self.labels},
            timeout=get_config().rpc_connect_timeout_s)
        # a (re-)registered node is ALIVE CP-side; a drain that was in
        # flight across a CP restart is forgotten by both ends together
        self._draining = False

    def _report_resources(self):
        """Versioned resource report (ref: RaySyncer versioned views,
        ray_syncer.h:87): every snapshot carries a monotonically increasing
        version so the CP can discard stale/reordered updates — notify-based
        reports race heartbeats, and an out-of-order apply would regress the
        CP's availability view."""
        with self._lock:
            self._res_version += 1
            body = {"node_id": self.node_id,
                    "available": dict(self.available),
                    "version": self._res_version}
        try:
            # versioned heartbeat: a lost report self-heals on the next
            # periodic report (the CP keeps the highest version it saw)
            # graftlint: fire-and-forget
            self._pool.get(self.cp_addr).notify("report_resources", body)
        except Exception:
            pass
        return body["version"]

    # ------------------------------------------------------------------
    def _handle(self, method: str, body, peer):
        fn = getattr(self, "_h_" + method, None)
        if fn is None:
            raise ValueError(f"node agent: unknown method {method}")
        return fn(body)

    def _h_ping(self, body):
        return {"ok": True}

    # ---- graceful drain (ref: node_manager.proto:448 DrainRaylet) ------
    def _h_drain(self, body):
        """CP tells us we are DRAINING: stop granting leases (waiters wake
        and redirect/refuse) but let in-flight work run to completion —
        the CP's drain finisher polls drain_status until we are idle."""
        self._draining = True
        with self._lock:
            self._lease_cv.notify_all()
        return {"ok": True}

    def _h_drain_status(self, body):
        """Drain progress for the CP finisher and `ray-tpu status`."""
        with self._lock:
            return {"draining": self._draining,
                    "inflight_leases": len(self._leases),
                    "busy_workers": sum(
                        1 for w in self._workers.values() if w.busy)}

    def _h_drain_objects(self, body):
        """Re-home primary copies: every sealed object this store holds for
        a live owner is pulled BY the target node (chunked, admission-
        controlled — the same path as any remote read), then the owner is
        told the copy moved so later gets resolve to the survivor instead
        of a gone node. Blocking method: migration streams real bytes."""
        target_addr = tuple(body["target_addr"])
        target_node = body.get("target_node_id")
        target = self._pool.get(target_addr)
        with self._lock:
            owned = dict(self._object_owners)
        moved = failed = 0
        for oid, owner in owned.items():
            if self._stopped.is_set():
                break
            if not self.store.contains(oid):
                continue
            try:
                r = target.call(
                    "pull_object",
                    {"object_id": oid, "from_addr": self.addr,
                     "owner_addr": owner}, timeout=120.0)
            except Exception:  # noqa: BLE001 - count and keep going
                r = None
            if not (r and r.get("ok")):
                failed += 1
                continue
            moved += 1
            if owner is not None and target_node is not None:
                # Acknowledged call: the owner's location table MUST learn
                # the copy moved — this node deregisters right after the
                # drain, and an owner still pointing here would direct
                # readers at a dead node. A lost one-way notify does
                # exactly that, silently.
                try:
                    self._pool.get(tuple(owner)).call(
                        "object_moved",
                        {"object_id": oid, "node_id": target_node,
                         "from_node_id": self.node_id}, timeout=5.0)
                except Exception:  # noqa: BLE001 - owner may be gone
                    pass
        return {"ok": True, "moved": moved, "failed": failed}

    # ---- cross-node mutable channels (ref: node_manager.proto:509-512
    # RegisterMutableObject/PushMutableObject) -------------------------
    def _h_channel_relay_open(self, body):
        """Writer-node side: start relaying one reader slot of a local
        channel to a shadow channel on another node's agent. A reader index
        has ONE live attachment: re-attaching (consumer restarted elsewhere)
        replaces the previous relay; a value already consumed by the old
        relay may be delivered to the old attachment."""
        key = (body["path"], int(body["index"]))
        stop = threading.Event()
        with self._lock:
            old = self._channel_relay_stops.pop(key, None)
            self._channel_relay_stops[key] = stop
        if old is not None:
            old.set()
        threading.Thread(
            target=self._channel_relay_loop,
            args=(body["path"], int(body["index"]),
                  tuple(body["target_agent"]), body["target_path"], stop),
            name="chan-relay", daemon=True).start()
        return {"ok": True}

    def _channel_relay_loop(self, path, index, target_agent, target_path,
                            relay_stop):
        from ray_tpu.core.channel import (
            ChannelClosedError,
            ChannelReader,
            ChannelTimeoutError,
        )
        reader = ChannelReader(path, index)
        client = self._pool.get(target_agent)
        while not self._stopped.is_set() and not relay_stop.is_set():
            try:
                data = reader.read(timeout=1.0, raw=True)
            except ChannelTimeoutError:
                continue
            except ChannelClosedError:
                try:
                    client.call("channel_close", {"path": target_path},
                                timeout=10.0)
                except Exception:  # noqa: BLE001 - consumer may be gone
                    pass
                return
            except OSError:
                return  # writer unlinked the segment
            try:
                # synchronous push: the shadow write blocks until the
                # consumer acks, carrying backpressure upstream (our ack
                # above releases the writer slot only once per relayed value)
                client.call("channel_push",
                            {"path": target_path, "data": data},
                            timeout=600.0)
            except Exception as e:  # noqa: BLE001 - consumer died/stalled
                # close the shadow so the consumer sees ChannelClosedError
                # instead of blocking forever on a relay that will never
                # deliver again (the in-hand value is lost — log it)
                logger.warning(
                    "channel relay %s[%d] -> %s push failed (%r); closing "
                    "the shadow and stopping the relay", path, index,
                    target_path, e)
                try:
                    client.call("channel_close", {"path": target_path},
                                timeout=10.0)
                except Exception:  # noqa: BLE001 - consumer gone entirely
                    pass
                return

    def _h_channel_push(self, body):
        from ray_tpu.core.channel import Channel
        path = body["path"]
        with self._lock:
            ch = self._relay_channels.get(path)
            if ch is None:
                ch = self._relay_channels[path] = Channel(0, 0, _attach=path)
        ch.write(body["data"], timeout=600.0)
        return {"ok": True}

    def _h_channel_close(self, body):
        from ray_tpu.core.channel import Channel
        path = body["path"]
        with self._lock:
            ch = self._relay_channels.pop(path, None)
        if ch is None:
            try:
                ch = Channel(0, 0, _attach=path)
            except OSError:
                return {"ok": False}
        ch.close()
        return {"ok": True}

    def _h_dump_node_stacks(self, body):
        """Stack snapshot of the agent AND every registered worker on this
        node (ref: dashboard reporter profiling endpoints). A worker that
        doesn't answer within the per-worker budget is reported as such —
        exactly the workers you most want flagged."""
        from concurrent.futures import ThreadPoolExecutor

        from ray_tpu.observability.profiling import dump_thread_stacks
        out = {"agent": dump_thread_stacks()}
        with self._lock:
            targets = [(w.hex()[:12], i.addr) for w, i in
                       self._workers.items() if i.addr is not None]

        def probe(item):
            wid, addr = item
            try:
                r = self._pool.get(tuple(addr)).call(
                    "dump_stacks", None, timeout=5.0, connect_timeout=2.0)
                return wid, r.get("stacks", "")
            except Exception as e:  # noqa: BLE001
                return wid, f"<unreachable: {e!r}>"

        if targets:
            # concurrent: N wedged workers must cost ~one per-worker budget,
            # not N of them serially (the caller's timeout would fire and
            # lose the whole node's dump — the diagnostic you needed most)
            with ThreadPoolExecutor(max_workers=min(16, len(targets))) as ex:
                for wid, text in ex.map(probe, targets):
                    out[f"worker-{wid}"] = text
        return out

    def _fanout_workers(self, method: str, body, timeout: float) -> dict:
        """Call ``method`` on every registered worker with an RPC address
        (same shape as _h_dump_node_stacks: concurrent, per-worker budget,
        unreachable workers reported instead of failing the node)."""
        from concurrent.futures import ThreadPoolExecutor

        with self._lock:
            targets = [(w.hex(), i.addr) for w, i in
                       self._workers.items() if i.addr is not None]

        def probe(item):
            wid, addr = item
            try:
                return wid, self._pool.get(tuple(addr)).call(
                    method, body, timeout=timeout, connect_timeout=2.0)
            except Exception as e:  # noqa: BLE001
                return wid, {"ok": False, "error": repr(e)}

        out: dict[str, dict] = {}
        if targets:
            with ThreadPoolExecutor(max_workers=min(16, len(targets))) as ex:
                for wid, res in ex.map(probe, targets):
                    out[wid] = res
        return out

    def _h_profiling_start(self, body):
        """Start an XPlane capture on every worker process of this node
        (the per-node hop of the cluster-wide `ray-tpu profile` path)."""
        return {"node_id": self.node_id.hex(),
                "workers": self._fanout_workers(
                    "profiling_start", body or {}, timeout=15.0)}

    def _h_profiling_stop(self, body):
        """Stop the active captures; per-worker results carry the trace
        logdirs the caller registers as artifacts."""
        return {"node_id": self.node_id.hex(),
                "workers": self._fanout_workers(
                    "profiling_stop", body or {}, timeout=30.0)}

    def _h_save_device_memory_profile(self, body):
        """Device-memory (pprof) dump on every worker of this node."""
        return {"node_id": self.node_id.hex(),
                "workers": self._fanout_workers(
                    "save_device_memory_profile", body or {}, timeout=30.0)}

    # ---- worker pool ---------------------------------------------------
    def _spawn_inproc_worker(self, for_tpu: bool,
                             runtime_env: dict | None) -> _WorkerInfo:
        """In-process spawn: a WorkerRuntime hosted on threads in THIS
        process, registered synchronously (no call-home round trip).
        Process-level runtime_env isolation does not apply — acceptable for
        the scale/autoscaler harness this mode exists for."""
        from ray_tpu.core.ids import JobID
        from ray_tpu.core.worker import WorkerRuntime
        from ray_tpu.runtime_env import env_hash

        worker_id = WorkerID.from_random()
        rt = WorkerRuntime(
            mode="worker", cp_addr=self.cp_addr, agent_addr=self.addr,
            job_id=JobID.from_int(0), worker_id=worker_id,
            node_id=self.node_id)
        handle = _InProcHandle(rt)
        rt.on_exit = handle.exit
        info = _WorkerInfo(worker_id=worker_id, is_tpu_worker=for_tpu,
                           env_key=env_hash(runtime_env))
        info.ready = threading.Event()
        info.proc = handle
        info.pid = os.getpid()
        info.addr = rt.addr
        with self._lock:
            self._workers[worker_id] = info
            info.ready.set()
            self._lease_cv.notify_all()
        return info

    def _spawn_worker(self, for_tpu: bool = False,
                      runtime_env: dict | None = None) -> _WorkerInfo:
        from ray_tpu.runtime_env import env_hash, materialize_runtime_env

        if self._inproc_workers:
            return self._spawn_inproc_worker(for_tpu, runtime_env)
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        cwd = os.getcwd()
        # the framework must stay importable even when a runtime_env moves
        # the worker's cwd (source-tree installs aren't on sys.path then)
        from ray_tpu.core.config import package_parent_path
        env["PYTHONPATH"] = (package_parent_path() + os.pathsep
                             + env.get("PYTHONPATH", ""))
        python_exe = sys.executable
        if runtime_env:
            # materialize BEFORE spawn (reference: runtime_env agent creates
            # the env, then the worker starts inside it)
            env_vars, env_cwd, pypath, venv_py, container = \
                materialize_runtime_env(
                    self._pool.get(self.cp_addr), runtime_env)
            env.update(env_vars)
            if env_cwd:
                cwd = env_cwd
            if pypath:
                env["PYTHONPATH"] = os.pathsep.join(
                    pypath + [env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
            if venv_py:
                # pip envs: the worker runs on the spec's virtualenv
                # interpreter, so its installed packages shadow the base
                # environment's (reference pip/uv plugin semantics)
                python_exe = venv_py
            # pin every cache entry this worker will run out of: the env
            # GC must never rmtree a live worker's cwd/py_modules/venv
            # (unpinned when the agent reaps the worker)
            from ray_tpu.runtime_env.packaging import pin_env_paths
            pin_paths = list(pypath)
            if env_cwd:
                pin_paths.append(env_cwd)
            if venv_py:
                # <env_root>/venv-<key>/bin/python -> the venv entry dir
                pin_paths.append(
                    os.path.dirname(os.path.dirname(venv_py)))
            pin_env_paths(worker_id.hex(), pin_paths)
        # see ray_tpu/__init__.py: arrow's mimalloc pool is unsafe under the
        # worker's thread profile; pin the system pool unless the user set one
        env.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")
        env["RAY_TPU_CP_ADDR"] = f"{self.cp_addr[0]}:{self.cp_addr[1]}"
        env["RAY_TPU_AGENT_ADDR"] = f"{self.addr[0]}:{self.addr[1]}"
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        if not for_tpu:
            # CPU-pool workers must never grab the TPU chips as an import side
            # effect (single-process-per-chipset constraint). Dropping the
            # TPU plugin bootstrap env also skips the sitecustomize-time jax
            # import (~2.5s), so CPU worker spawn is fast; jax is imported
            # lazily (CPU backend) only if a task actually uses it.
            from ray_tpu.core.cpu_env import scrub_tpu_env
            scrub_tpu_env(env)
        info = _WorkerInfo(worker_id=worker_id, is_tpu_worker=for_tpu,
                           env_key=env_hash(runtime_env))
        info.ready = threading.Event()
        # Per-worker log files (ref: /tmp/ray/session_*/logs +
        # _private/log_monitor.py); stderr/stdout land here, readable via
        # `ray_tpu.util.state.worker_logs()`.
        log_dir = get_config().log_dir or os.path.join(
            "/tmp/ray_tpu_logs", f"agent-{os.getpid()}")
        os.makedirs(log_dir, exist_ok=True)
        out_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.out")
        err_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.err")
        argv = [python_exe, "-m", "ray_tpu.core.worker_main"]
        if runtime_env and container:
            # image_uri envs: the worker runs inside the container (shm +
            # host network shared — the object plane and RPC addresses keep
            # working; reference image_uri.py worker-in-container). The
            # container list ends with the image; worker identity env vars
            # are forwarded explicitly.
            env_flags: list[str] = []
            for k, v in env.items():
                if k.startswith(("RAY_TPU_", "PYTHONPATH", "ARROW_")):
                    env_flags += ["-e", f"{k}={v}"]
            argv = container[:-1] + env_flags + [
                container[-1], "python", "-m", "ray_tpu.core.worker_main"]
        with open(out_path, "ab") as fout, open(err_path, "ab") as ferr:
            proc = subprocess.Popen(
                argv, env=env, cwd=cwd, stdout=fout, stderr=ferr)
        info.proc, info.pid = proc, proc.pid
        info.log_paths = (out_path, err_path)
        with self._lock:
            self._workers[worker_id] = info
        return info

    def _system_metrics(self) -> dict:
        """Per-node system gauges shipped with the heartbeat and exported at
        the control plane's prometheus endpoint (TPU-native analog of the
        reference's per-node ReporterAgent -> MetricsAgent pipeline,
        dashboard/modules/reporter/reporter_agent.py + stats/metric_defs.cc)."""
        with self._lock:
            workers = list(self._workers.values())
            leases = len(self._leases)
        m = {
            "workers_total": len(workers),
            "workers_busy": sum(1 for w in workers if w.busy),
            "workers_actor": sum(1 for w in workers
                                 if w.actor_id is not None),
            "leases_active": leases,
        }
        try:
            st = self.store.stats()
            m["object_store_used_bytes"] = st.get("used_bytes", 0)
            m["object_store_num_objects"] = st.get("num_objects", 0)
            m["object_store_capacity_bytes"] = getattr(
                self.store, "capacity", 0)
        except Exception:  # noqa: BLE001 - store impl without counters
            pass
        m["object_store_num_spilled"] = getattr(self.store, "num_spilled", 0)
        # mirror into the flusher registry (the heartbeat copy feeds the CP
        # exposition's per-node gauges; these feed the time-series store)
        _WORKER_COUNT_GAUGE.set(m["workers_total"], tags={"state": "total"})
        _WORKER_COUNT_GAUGE.set(m["workers_busy"], tags={"state": "busy"})
        _WORKER_COUNT_GAUGE.set(m["workers_actor"], tags={"state": "actor"})
        _STORE_SPILLED_GAUGE.set(m["object_store_num_spilled"])
        try:
            from ray_tpu.runtime_env.packaging import env_cache_size
            _ENV_CACHE_GAUGE.set(env_cache_size())
        except Exception:  # noqa: BLE001 - gauge only
            pass
        for k, v in self.resources_total.items():
            m[f"resource_total:{k}"] = float(v)
        with self._lock:
            for k, v in self.available.items():
                m[f"resource_available:{k}"] = float(v)
        return m

    def _log_monitor_loop(self):
        """Tail per-worker log files and publish new lines to the CP
        "worker_logs" channel, where driver runtimes print them (TPU-native
        analog of the reference's log monitor, _private/log_monitor.py: files
        -> GCS pubsub -> driver stdout)."""
        interval = get_config().log_monitor_interval_s
        while not self._stopped.wait(interval):
            with self._lock:
                targets = [w for w in self._workers.values()
                           if w.log_paths and w.job_id]
            for info in targets:
                for i, path in enumerate(info.log_paths):
                    try:
                        with open(path, "rb") as f:
                            f.seek(info.log_offsets[i])
                            data = f.read(256 * 1024)
                    except OSError:
                        continue
                    if not data:
                        continue
                    # consume only whole lines; an unterminated tail stays in
                    # the file for the next tick (a straddled write must not
                    # surface as two broken lines / torn UTF-8). Pathological
                    # newline-free output still flushes once it tops 64KB.
                    nl = data.rfind(b"\n")
                    if nl < 0 and len(data) < 64 * 1024:
                        continue
                    data = data if nl < 0 else data[:nl + 1]
                    info.log_offsets[i] += len(data)
                    lines = data.decode("utf-8", "replace").splitlines()
                    for lo in range(0, len(lines), 200):
                        try:
                            # lossy log streaming by design — dropping a
                            # chunk under CP outage beats stalling the
                            # log monitor loop
                            # graftlint: fire-and-forget
                            self._pool.get(self.cp_addr).notify("publish", {
                                "channel": f"worker_logs:{info.job_id}",
                                "msg": {"node_id": self.node_id.hex()[:8],
                                        "pid": info.pid,
                                        "stream": ("out", "err")[i],
                                        "actor": (info.actor_id.hex()[:8]
                                                  if info.actor_id else None),
                                        "lines": lines[lo:lo + 200]}})
                        except Exception:
                            break

    def _h_worker_ready(self, body):
        """Worker process calls home after starting its RPC server."""
        with self._lock:
            info = self._workers.get(body["worker_id"])
            if info is None:
                info = _WorkerInfo(worker_id=body["worker_id"])
                info.ready = threading.Event()
                self._workers[body["worker_id"]] = info
            info.addr = tuple(body["addr"])
            info.pid = body.get("pid", info.pid)
            info.ready.set()
            self._lease_cv.notify_all()
        return {"ok": True, "node_id": self.node_id}

    def _pop_idle_worker(self, for_tpu: bool,
                         env_key: str = "") -> _WorkerInfo | None:
        for info in self._workers.values():
            if (info.addr is not None and not info.busy and info.actor_id is None
                    and info.is_tpu_worker == for_tpu
                    and info.env_key == env_key):
                return info
        return None

    def _h_lease_worker(self, body):
        """Blocking lease grant (ref: HandleRequestWorkerLease
        node_manager.cc:1627). Reply: granted | redirect (spillback) | timeout.

        The resource reservation is taken once and HELD while a worker spawns —
        a competing request that cannot reserve redirects to another node
        immediately instead of fighting over the pool (the reference's
        queue-then-spillback in ClusterLeaseManager)."""
        cfg = get_config()
        resources = dict(body.get("resources") or {})
        pg_id = body.get("pg_id")
        bundle_index = body.get("bundle_index", -1)
        for_actor = body.get("for_actor")
        runtime_env = body.get("runtime_env")
        from ray_tpu.runtime_env import env_hash
        env_key = env_hash(runtime_env)
        for_tpu = resources.get("TPU", 0) > 0
        deadline = time.monotonic() + body.get("timeout", cfg.lease_timeout_s)
        # When nothing can be reserved and no spillback target exists, reply
        # `busy` after a short grace instead of blocking out the full
        # timeout: the caller then opens its per-worker pipelining depth
        # (submitter MAX_INFLIGHT_PER_WORKER) rather than waiting on a lease
        # that may be a minute away.
        busy_deadline = time.monotonic() + min(
            0.5, body.get("timeout", cfg.lease_timeout_s))
        reserved = False
        spawned = False
        spawned_wid = None  # THIS lease's spawn (reap is per-lease)
        try:
            while not self._stopped.is_set():
                if self._draining:
                    # draining nodes take no new work: spill the request to
                    # a peer when possible, refuse otherwise (the caller
                    # retries through the CP, whose view excludes us)
                    if pg_id is None:
                        target = self._find_remote_node(resources)
                        if target is not None:
                            _SPILLBACK_COUNTER.inc()
                            return {"granted": False, "redirect": target}
                    return {"granted": False, "draining": True}
                need_spawn = False
                try_redirect = False
                evict_proc = None
                with self._lock:
                    # reap spawns that died BEFORE registering (e.g. killed
                    # by chaos mid-boot): without this, `spawned` stays set
                    # and the lease waits out its full timeout on a corpse.
                    # Only OUR OWN dead spawn resets our flag — resetting on
                    # any death would double-spawn for other live leases.
                    dead = [wid for wid, i in self._workers.items()
                            if i.proc is not None and i.addr is None
                            and i.proc.poll() is not None]
                    for wid in dead:
                        del self._workers[wid]
                        self._unpin_worker_envs(wid)
                    # not "in dead": a CONCURRENT lease loop may have reaped
                    # our corpse in its own iteration — absence from the
                    # pool is the durable signal (a healthy registered spawn
                    # stays in the dict). Same for THEFT: another concurrent
                    # lease may legally pop OUR spawn the moment it
                    # registers (the pool is fungible); if our spawn is
                    # gone, dead, or taken, we must become spawn-eligible
                    # again or we'd wait out the full lease timeout with
                    # `spawned` set on a worker we'll never get.
                    if spawned and spawned_wid is not None:
                        w = self._workers.get(spawned_wid)
                        if w is None or w.busy or w.actor_id is not None:
                            spawned = False
                            spawned_wid = None
                    if not reserved:
                        reserved = self._try_reserve(resources, pg_id, bundle_index)
                    if reserved:
                        worker = self._pop_idle_worker(for_tpu, env_key)
                        if worker is not None and worker.ready.is_set():
                            worker.busy = True
                            worker.job_id = body.get("job_id") or worker.job_id
                            if for_actor is not None:
                                worker.actor_id = for_actor
                            lease = _Lease(uuid.uuid4().hex, worker.worker_id,
                                           resources, pg_id, bundle_index,
                                           lessee=body.get("lessee"))
                            self._leases[lease.lease_id] = lease
                            reserved = False  # consumed by the lease
                            grant_version = self._report_resources()
                            # snapshot rides the reply so the caller can SET
                            # its view instead of subtracting (a subtract
                            # after our async report double-counts the lease
                            # and can wedge the view at 0)
                            return {"granted": True, "lease_id": lease.lease_id,
                                    "worker_id": worker.worker_id,
                                    "worker_addr": worker.addr,
                                    "available": dict(self.available),
                                    "version": grant_version}
                        if not spawned and self._can_spawn(for_tpu):
                            spawned = need_spawn = True
                        elif not spawned:
                            # pool is at its cap but holds idle workers for
                            # OTHER runtime envs: evict one to make room, or
                            # an env-mismatched burst starves this lease
                            # until its timeout
                            victim = next(
                                (i for i in self._workers.values()
                                 if i.addr is not None and not i.busy
                                 and i.actor_id is None
                                 and i.is_tpu_worker == for_tpu
                                 and i.env_key != env_key), None)
                            if victim is not None:
                                victim.busy = True  # unleaseable while dying
                                del self._workers[victim.worker_id]
                                self._unpin_worker_envs(victim.worker_id)
                                evict_proc = victim.proc
                                spawned = need_spawn = True
                    elif pg_id is None:
                        try_redirect = True
                if evict_proc is not None:
                    try:
                        evict_proc.terminate()
                    except Exception:  # noqa: BLE001 - already gone
                        pass
                if need_spawn:
                    spawned_wid = self._spawn_worker(
                        for_tpu, runtime_env).worker_id
                if try_redirect:
                    target = self._find_remote_node(resources)
                    if target is not None:
                        _SPILLBACK_COUNTER.inc()
                        return {"granted": False, "redirect": target}
                    if time.monotonic() > busy_deadline:
                        return {"granted": False, "busy": True}
                with self._lock:
                    self._lease_cv.wait(timeout=0.05)
                if time.monotonic() > deadline:
                    logger.warning(
                        "lease timeout: res=%s reserved=%s spawned=%s "
                        "env_key=%r available=%s workers=%s", resources,
                        reserved, spawned, env_key, self.available,
                        [(w.hex()[:6], i.busy, i.actor_id is not None,
                          i.addr is not None, i.env_key)
                         for w, i in self._workers.items()])
                    return {"granted": False, "timeout": True}
            return {"granted": False, "timeout": True}
        finally:
            if reserved:
                with self._lock:
                    self._unreserve(resources, pg_id, bundle_index)
                    self._lease_cv.notify_all()

    def _can_spawn(self, for_tpu: bool) -> bool:
        """Concurrent leases are bounded by the CPU resource, so the pool
        never needs more workers than logical CPUs (+ headroom for
        zero-CPU leases); spawn-ahead is also bounded so a burst of lease
        requests can't fork dozens of interpreters at once and thrash the
        host (ref: worker_pool.h maximum_startup_concurrency)."""
        cfg = get_config()
        mine = [w for w in self._workers.values()
                if w.is_tpu_worker == for_tpu]
        if for_tpu:
            # one TPU worker process per chip group (hard-part 7)
            return len(mine) < 1
        cpus = int(self.resources_total.get("CPU", 4))
        # Actors each occupy a dedicated worker for life and are gated by
        # the resource scheduler, so only POOL (non-actor) workers count
        # against the cap — otherwise N zero-CPU actors would starve task
        # leases (and vice versa).
        pool = [w for w in mine if w.actor_id is None]
        limit = cfg.max_workers_per_node or (cpus + 4)
        if len(pool) >= limit:
            return False
        starting = sum(1 for w in pool if w.addr is None)
        return starting < max(2, cpus // 2)

    def _try_reserve(self, resources, pg_id, bundle_index) -> bool:
        if pg_id is not None:
            pg = self._pg_reserved.get(pg_id)
            if pg is None:
                return False
            if bundle_index >= 0:
                pool = pg.get(bundle_index)
                if pool is None or not fits(pool, resources):
                    return False
                subtract(pool, resources)
                return True
            for pool in pg.values():
                if fits(pool, resources):
                    subtract(pool, resources)
                    return True
            return False
        if not fits(self.available, resources):
            return False
        subtract(self.available, resources)
        return True

    def _unreserve(self, resources, pg_id, bundle_index):
        if pg_id is not None:
            pg = self._pg_reserved.get(pg_id)
            if pg is None:
                return
            if bundle_index >= 0 and bundle_index in pg:
                add(pg[bundle_index], resources)
            elif pg:
                add(next(iter(pg.values())), resources)
            return
        add(self.available, resources)

    def _find_remote_node(self, resources) -> tuple | None:
        try:
            nodes = self._pool.get(self.cp_addr).call("get_nodes", None, timeout=5.0)
        except Exception:
            return None
        for n in nodes:
            if n["node_id"] == self.node_id or not n["alive"] \
                    or n.get("state", "ALIVE") != "ALIVE":
                continue
            if fits(n["available"], resources):
                return tuple(n["addr"])
        return None

    def _h_return_lease(self, body):
        with self._lock:
            lease = self._leases.pop(body["lease_id"], None)
            if lease is None:
                return {"ok": False}
            self._unreserve(lease.resources, lease.pg_id, lease.bundle_index)
            worker = self._workers.get(lease.worker_id)
            if worker is not None and worker.actor_id is None:
                worker.busy = False
                worker.idle_since = time.monotonic()
            self._lease_cv.notify_all()
        self._report_resources()
        return {"ok": True}

    def _h_worker_blocked(self, body):
        """A leased worker blocked in get(); release its CPU so nested tasks
        can run (ref: the raylet's blocked-worker resource release)."""
        with self._lock:
            for lease in self._leases.values():
                if lease.worker_id == body["worker_id"]:
                    cpus = {"CPU": lease.resources.get("CPU", 0.0)}
                    if cpus["CPU"] > 0:
                        self._unreserve(cpus, lease.pg_id, lease.bundle_index)
                        lease.resources = {**lease.resources, "CPU": 0.0}
                    self._lease_cv.notify_all()
                    break
        return {"ok": True}

    # ---- placement group bundles --------------------------------------
    def _h_prepare_bundles(self, body):
        """Phase 1 (ref: node_manager.proto:452 PrepareBundleResources)."""
        pg_id = body["pg_id"]
        with self._lock:
            need: dict[str, float] = {}
            for _, b in body["bundles"]:
                for k, v in b.items():
                    need[k] = need.get(k, 0.0) + v
            if not fits(self.available, need):
                return {"ok": False}
            subtract(self.available, need)
            self._pg_prepared[pg_id] = {i: dict(b) for i, b in body["bundles"]}
        self._report_resources()
        return {"ok": True}

    def _h_commit_bundles(self, body):
        """Phase 2 (ref: node_manager.proto:457 CommitBundleResources)."""
        pg_id = body["pg_id"]
        with self._lock:
            prepared = self._pg_prepared.pop(pg_id, None)
            if prepared is None:
                return {"ok": False}
            self._pg_reserved[pg_id] = prepared
            self._lease_cv.notify_all()
        return {"ok": True}

    def _h_cancel_bundles(self, body):
        """(ref: node_manager.proto:461 CancelResourceReserve)"""
        pg_id = body["pg_id"]
        with self._lock:
            pools = self._pg_prepared.pop(pg_id, None) or self._pg_reserved.pop(pg_id, None)
            if pools:
                for pool in pools.values():
                    add(self.available, pool)
            # Live leases under this pg become plain node leases; their
            # resources return to `available` when the lease returns. No
            # adjustment here: prepare subtracted the FULL bundle from
            # `available`, and the pools we just added back held only the
            # unleased remainder — the leased share stays owed until lease
            # return (subtracting again would double-count it).
            for lease in self._leases.values():
                if lease.pg_id == pg_id:
                    lease.pg_id = None
                    lease.bundle_index = -1
            self._lease_cv.notify_all()
        self._report_resources()
        return {"ok": True}

    # ---- object store --------------------------------------------------
    def _h_store_create(self, body):
        name, offset = self.store.create(body["object_id"], body["size"],
                                         body.get("device_hint", ""))
        if body["size"] > 0:
            _STORE_BYTES_STORED.inc(body["size"])
        if body.get("owner_addr") is not None:
            self._object_owners[body["object_id"]] = tuple(body["owner_addr"])
        return {"shm_name": name, "offset": offset}

    def _h_store_seal(self, body):
        self.store.seal(body["object_id"])
        return {"ok": True}

    def _h_store_get_meta(self, body):
        meta = self.store.get_meta(body["object_id"])
        (_STORE_HITS if meta is not None else _STORE_MISSES).inc()
        return meta

    def _h_store_read_done(self, body):
        """Reader finished deserializing: release its read lease so the
        spill/delete paths may touch the extent again."""
        read_done = getattr(self.store, "read_done", None)
        if read_done is not None:
            read_done(body["object_id"])
        return {"ok": True}

    def _h_store_contains(self, body):
        return self.store.contains(body["object_id"])

    def _h_store_pin(self, body):
        self.store.pin(body["object_id"], body.get("pinned", True))
        return {"ok": True}

    def _h_store_delete(self, body):
        self._object_owners.pop(body["object_id"], None)
        self.store.delete(body["object_id"])
        return {"ok": True}

    def _h_store_stats(self, body):
        return self.store.stats()

    def _h_read_object(self, body):
        """Chunked remote read (ref: object_manager.proto:60 Pull/Push)."""
        out = self.store.read_bytes(
            body["object_id"], body.get("offset", 0), body.get("size"))
        if out is None:
            return None
        total, chunk = out
        return {"total": total, "data": chunk}

    def _admit_pull(self, nbytes: int) -> bool:
        """Admission control: bound total in-flight pull bytes so N
        concurrent large pulls can't blow host memory / flood the network
        (ref: pull_manager.h:49 PullManager quota). Blocking-methods
        handlers run on dedicated threads, so waiting here is safe.
        Returns False (nothing reserved) if the agent is shutting down."""
        limit = get_config().max_inflight_pull_bytes
        with self._pull_cv:
            while self._pull_inflight_bytes + nbytes > limit \
                    and self._pull_inflight_bytes > 0:
                self._pull_cv.wait(timeout=1.0)
                if self._stopped.is_set():
                    return False
            self._pull_inflight_bytes += nbytes
        return True

    def _release_pull(self, nbytes: int) -> None:
        with self._pull_cv:
            self._pull_inflight_bytes -= nbytes
            self._pull_cv.notify_all()

    def _h_pull_object(self, body):
        """Fetch an object from a remote node's store into the local store
        (ref: pull_manager.h:49). Chunks stream straight into the local
        store allocation — peak host memory is one chunk, not the object.
        Concurrent pulls of the same object are deduplicated: followers
        wait for the leader instead of racing the chunk writes."""
        object_id = body["object_id"]
        if self.store.contains(object_id):
            _STORE_HITS.inc()
            return {"ok": True}
        _STORE_MISSES.inc()
        # single-flight per object (ref: PullManager object-level dedup)
        with self._pull_cv:
            leader = object_id not in self._pulls_in_progress
            if leader:
                self._pulls_in_progress[object_id] = threading.Event()
            event = self._pulls_in_progress[object_id]
        if not leader:
            event.wait(timeout=300.0)
            return {"ok": self.store.contains(object_id)}
        try:
            return self._pull_as_leader(body, object_id)
        finally:
            with self._pull_cv:
                self._pulls_in_progress.pop(object_id, None)
            event.set()

    def _pull_as_leader(self, body, object_id):
        remote = self._pool.get(tuple(body["from_addr"]))
        chunk = 8 * 1024 * 1024
        first = remote.call_with_retry(
            "read_object", {"object_id": object_id, "offset": 0, "size": chunk},
            timeout=60.0)
        if first is None:
            return {"ok": False}
        total = first["total"]
        if not self._admit_pull(total):
            return {"ok": False}
        try:
            self.store.write_chunk(object_id, 0, first["data"], total)
            off = len(first["data"])
            while off < total:
                part = remote.call_with_retry(
                    "read_object",
                    {"object_id": object_id, "offset": off, "size": chunk},
                    timeout=60.0)
                if part is None:
                    self.store.delete(object_id)
                    return {"ok": False}
                self.store.write_chunk(object_id, off, part["data"], total)
                off += len(part["data"])
        finally:
            self._release_pull(total)
        if body.get("owner_addr") is not None:
            self._object_owners[object_id] = tuple(body["owner_addr"])
        return {"ok": True}

    def _on_store_evict(self, object_id):
        """Tell the owner its primary copy on this node is gone so lineage
        reconstruction can kick in (ref: object_recovery_manager.h:41)."""
        owner = self._object_owners.pop(object_id, None)
        if owner is not None:
            try:
                # advisory: an owner that misses this learns the location
                # is gone on its next failed pull and re-discovers/respawns
                # via lineage — eviction is not a drain (no deregistration)
                # graftlint: fire-and-forget
                self._pool.get(owner).notify(
                    "object_lost", {"object_id": object_id, "node_id": self.node_id})
            except Exception:
                pass

    # ---- worker monitoring ----------------------------------------------
    def _monitor_workers(self):
        cfg = get_config()
        hb_interval = cfg.agent_heartbeat_interval_s
        last_report = 0.0
        while not self._stopped.is_set():
            time.sleep(0.1)
            # periodic resource heartbeat (ref: RaySyncer resource view
            # gossip, ray_syncer.h:87): self-heals any CP-view drift from
            # report/subtract races, and re-registers after a CP restart
            # (NotifyGCSRestart analog)
            now = time.monotonic()
            if now - last_report >= hb_interval:
                last_report = now
                try:
                    with self._lock:
                        self._res_version += 1
                        hb = {"node_id": self.node_id,
                              "available": dict(self.available),
                              "version": self._res_version}
                    hb["metrics"] = self._system_metrics()
                    r = self._pool.get(self.cp_addr).call(
                        "heartbeat", hb, timeout=5.0)
                    if r is not None and not r.get("known", True):
                        logger.info("control plane lost this node "
                                    "(restart?); re-registering")
                        self._register_with_cp()
                    elif r is not None \
                            and r.get("state") in ("DRAINING", "DRAINED") \
                            and not self._draining:
                        # the CP's drain notify was lost: the heartbeat
                        # reply is the backstop delivery channel
                        self._h_drain({})
                except Exception:
                    pass
            if self._memory_monitor is not None:
                with self._lock:
                    snapshot = list(self._workers.values())
                self._memory_monitor.maybe_kill(snapshot)
            dead: list[_WorkerInfo] = []
            with self._lock:
                for info in list(self._workers.values()):
                    if info.proc is not None and info.proc.poll() is not None:
                        dead.append(info)
                        del self._workers[info.worker_id]
                # reap long-idle workers
                now = time.monotonic()
                for info in list(self._workers.values()):
                    if (not info.busy and info.actor_id is None
                            and info.addr is not None
                            and now - info.idle_since > cfg.idle_worker_ttl_s):
                        try:
                            info.proc.terminate()
                        except Exception:
                            pass
            for info in dead:
                self._on_worker_dead(info)

    def _oom_kill_worker(self, info: _WorkerInfo, reason: str) -> None:
        """Hard-kill a worker under memory pressure; the normal dead-worker
        path (monitor loop) reaps it and notifies owners."""
        try:
            if info.proc is not None:
                info.proc.kill()
        except Exception:  # noqa: BLE001
            pass

    def _unpin_worker_envs(self, worker_id) -> None:
        """Release a reaped worker's runtime-env cache pins so the LRU GC
        may evict its entries again."""
        try:
            from ray_tpu.runtime_env.packaging import unpin_env_paths
            unpin_env_paths(worker_id.hex() if hasattr(worker_id, "hex")
                            else str(worker_id))
        except Exception:  # noqa: BLE001 — cleanup must not break reaping
            pass

    def _on_worker_dead(self, info: _WorkerInfo):
        code = info.proc.returncode if info.proc else None
        logger.info("worker %s (pid %s, actor=%s) died, exit code %s",
                    info.worker_id.hex()[:8], info.pid,
                    info.actor_id.hex()[:8] if info.actor_id else None, code)
        to_kill = []
        with self._lock:
            for lid, lease in list(self._leases.items()):
                # release leases ON the dead worker and leases HELD BY it
                # (a killed actor can't return the task leases it was
                # holding; leaking them wedges the node's resource view)
                if (lease.worker_id == info.worker_id
                        or lease.lessee == info.worker_id):
                    self._unreserve(lease.resources, lease.pg_id, lease.bundle_index)
                    del self._leases[lid]
                    w = self._workers.get(lease.worker_id)
                    if w is not None and lease.worker_id != info.worker_id \
                            and w.actor_id is None:
                        # the worker may still be mid-execution of the dead
                        # lessee's orphaned task — marking it idle would
                        # re-lease a busy CPU; terminate it instead (the
                        # monitor reaps + a fresh worker spawns clean)
                        to_kill.append(w.proc)
                        del self._workers[w.worker_id]
                        self._unpin_worker_envs(w.worker_id)
            self._lease_cv.notify_all()
        self._unpin_worker_envs(info.worker_id)
        for proc in to_kill:
            try:
                if proc is not None:
                    proc.terminate()
            except Exception:  # noqa: BLE001 - already gone
                pass
        self._report_resources()
        # ALWAYS tell the CP (not just for actors): a dead worker's metric
        # series must be retracted from the time-series store / exposition
        # even when it held no actor (ISSUE 4 metrics GC). Acknowledged
        # call, not one-way notify: metric retraction, kv-tier index
        # retraction, and actor-death fanout all hang off this message —
        # a notify dropped into a half-closed socket loses them silently.
        try:
            self._pool.get(self.cp_addr).call(
                "worker_died",
                {"worker_id": info.worker_id, "actor_id": info.actor_id,
                 "node_id": self.node_id,
                 "reason": f"worker process exited with code {code}"},
                timeout=5.0)
        except Exception:  # noqa: BLE001 — CP down; its own worker-death
            pass           # sweep (heartbeat miss) retracts eventually
        self._report_resources()

    # ---- lifecycle -------------------------------------------------------
    def _h_shutdown(self, body):
        threading.Thread(target=self.stop, daemon=True).start()
        return {"ok": True}

    def stop(self):
        self._stopped.set()
        with self._lock:
            workers = list(self._workers.values())
        for info in workers:
            if info.addr is not None:
                try:
                    # polite-exit hint only: the wait/kill loop below
                    # reaps every worker past the deadline regardless
                    # graftlint: fire-and-forget
                    self._pool.get(info.addr).notify(
                        "exit_worker", {"worker_id": info.worker_id})
                except Exception:
                    pass
        deadline = time.monotonic() + 2.0
        for info in workers:
            if info.proc is not None:
                try:
                    info.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except Exception:
                    try:
                        info.proc.kill()
                    except Exception:
                        pass
        # final metrics flush while the CP client pool is still open (clean
        # shutdown must not drop the last interval's deltas)
        if self._metrics_flusher is not None:
            _metrics.stop_flusher(self._metrics_flusher)
        else:
            _metrics.flush_now()
        self._server.stop()
        # the monitor thread reads store stats for heartbeats; it must be
        # gone before the native arena handle is destroyed (use-after-free
        # segfault otherwise)
        self._monitor_thread.join(timeout=5.0)
        self.store.shutdown()
        self._pool.close_all()
