"""Object plane tests: spilling, restore, chunked cross-node transfer
(reference: object_manager pull/push chunking object_manager.proto:60,
spilling local_object_manager.h:44, BASELINE 1-GiB broadcast row)."""

import numpy as np
import pytest

import ray_tpu


def test_spill_and_restore(ray_start_regular):
    """Objects past the store's high-water mark spill to disk (even pinned
    primaries) and restore transparently on get."""
    from ray_tpu.core import api

    agent = api._head[1]
    cap = agent.store.stats()["capacity_bytes"]
    obj = 1 << 25  # 32 MiB
    n = (cap // obj) + 6  # comfortably past capacity
    refs = [ray_tpu.put(np.full(obj, i % 251, np.uint8)) for i in range(n)]
    stats = agent.store.stats()
    assert stats["num_spilled"] > 0, "nothing spilled under pressure"
    assert stats["used_bytes"] <= cap
    # every object still readable — early ones restore from disk
    for i in (0, 1, n - 1):
        x = ray_tpu.get(refs[i])
        assert x[0] == i % 251 and x.nbytes == obj
    assert agent.store.stats()["num_restored"] > 0


@pytest.mark.slow
def test_large_object_broadcast_multinode():
    """A 1 GiB object produced on one node is pulled (chunked, admission-
    controlled) by consumers on three other nodes (BASELINE's
    1-GiB-broadcast row, at 4 nodes instead of 50)."""
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cap = 3 * (1 << 30) // 2  # 1.5 GiB per node: headroom over the payload
    cluster.add_node(num_cpus=2, resources={"src": 1},
                     object_store_memory=cap)
    for i in range(3):
        cluster.add_node(num_cpus=2, resources={f"dst{i}": 1},
                         object_store_memory=cap)
    ray_tpu.init(address=cluster.address)
    try:
        size = 1 << 30  # 1 GiB

        @ray_tpu.remote(resources={"src": 1})
        def produce():
            return np.arange(size // 8, dtype=np.float64)

        @ray_tpu.remote
        def consume(a):
            return float(a[:1000].sum()) + float(a[-1])

        ref = produce.remote()
        expect = float(np.arange(1000, dtype=np.float64).sum()) + (size // 8 - 1)
        outs = ray_tpu.get(
            [consume.options(resources={f"dst{i}": 1}).remote(ref)
             for i in range(3)], timeout=300)
        assert outs == [expect] * 3
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_device_resident_objects(ray_start_regular):
    """put(jax.Array) keeps the array resident in the owning process (get
    returns the SAME handle, no device->host round-trip); consumers in
    other processes that use jax receive a jax.Array (device_put on
    deserialize), others get numpy (never grabbing chips as a side effect).
    Ref: experimental/gpu_object_manager pass-by-reference semantics."""
    import jax
    import jax.numpy as jnp

    arr = jnp.arange(300_000, dtype=jnp.float32) * 2.0
    ref = ray_tpu.put(arr)

    # same-process get: identity, not a copy (zero-copy HBM handle)
    got = ray_tpu.get(ref)
    assert got is arr

    # cross-process consumer that imports jax sees a jax.Array
    @ray_tpu.remote
    def consume(a):
        import jax as j
        import jax.numpy as jn
        return (type(a).__module__, float(jn.sum(a[:10])))

    mod, s = ray_tpu.get(consume.remote(ref), timeout=60)
    assert s == float(sum(range(10))) * 2.0
    # the consumer imported jax BEFORE deserializing, so it gets jax.Array
    # (module path starts with jax*)
    assert mod.startswith("jax"), mod

    # freeing the ref releases the device-resident handle
    from ray_tpu.core import api
    rt = api._get_runtime()
    oid = ref.id()
    assert oid in rt._device_objects
    del ref, got
    import gc
    gc.collect()
    # __del__ only ENQUEUES the release (GC-reentrancy safety; see
    # object_ref.py) — it applies at the next runtime API call
    rt.drain_releases()
    assert oid not in rt._device_objects
