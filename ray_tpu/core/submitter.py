"""Caller-side task submission pipelines.

TPU-native analog of the reference's task submission layer
(/root/reference/src/ray/core_worker/task_submission/):

- ``NormalTaskSubmitter`` (normal_task_submitter.h:82): lease workers from the
  node agent, push tasks caller→executor directly (the agent is not on the data
  path), cache granted leases and reuse idle workers for queued tasks of the
  same shape (OnWorkerIdle, normal_task_submitter.cc:139), handle spillback
  redirects, and retry on worker failure.
- ``ActorTaskSubmitter`` (actor_task_submitter.cc): per-actor ordered pipeline —
  sequence numbers assigned at submit, sends over one TCP connection preserve
  order (sequential_actor_submit_queue.cc), pending tasks resubmitted on actor
  restart or failed with ActorDiedError on death (SendPendingTasks :223,339).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID
from ray_tpu.core.task_spec import DefaultStrategy, TaskSpec
from ray_tpu.exceptions import ActorDiedError, TaskError, WorkerCrashedError

logger = logging.getLogger(__name__)


@dataclass
class _ShapeState:
    queue: deque = field(default_factory=deque)
    leases: list = field(default_factory=list)     # list[_Lease]
    requests_in_flight: int = 0
    strategy: object = None
    runtime_env: dict | None = None
    last_busy: float = 0.0  # ts of last busy (saturated) lease reply


def _shape_key(spec: TaskSpec):
    """Tasks are queued per (resources, strategy, runtime_env) shape so a
    cached lease only serves tasks with identical placement constraints AND
    worker environment (reference worker_pool env-hash keying)."""
    from ray_tpu.runtime_env import env_hash
    pg = getattr(spec.strategy, "pg_id", None)
    idx = getattr(spec.strategy, "bundle_index", -1)
    s = spec.strategy
    strat_key: tuple = (type(s).__name__, env_hash(spec.runtime_env))
    if hasattr(s, "node_id_hex"):
        strat_key += (s.node_id_hex, s.soft)
    if hasattr(s, "hard"):
        strat_key += (frozenset(s.hard.items()), frozenset(s.soft.items()))
    return (frozenset(spec.resources.items()), pg, idx, strat_key)


@dataclass
class _Lease:
    lease_id: str
    agent_addr: tuple
    worker_addr: tuple
    worker_id: object
    inflight: int = 0  # pushed-not-replied tasks pipelined on this worker
    idle_since: float = 0.0  # monotonic ts when inflight last hit 0


class NormalTaskSubmitter:
    MAX_LEASES_PER_SHAPE = 16
    # Tasks pushed to one worker without waiting for replies (the reference's
    # max_tasks_in_flight_per_worker lease pipelining). Depth beyond 1 only
    # opens once no lease requests are outstanding — otherwise a 2-task burst
    # on a 2-node cluster would bind both tasks to the first granted worker
    # instead of spreading (and breadth is what the scheduler promised).
    MAX_INFLIGHT_PER_WORKER = 8
    # Granted leases linger briefly after their queue drains so sync
    # call-loops reuse a warm worker instead of re-leasing per task
    # (ref: worker lease idle keep-alive).
    IDLE_LEASE_TTL_S = 0.5

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._shapes: dict[object, _ShapeState] = {}
        self._lease_pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="lease")
        self._reaper = threading.Thread(
            target=self._reap_idle_leases, name="lease-reaper", daemon=True)
        self._stopped = threading.Event()
        self._reaper.start()

    def submit(self, spec: TaskSpec):
        key = _shape_key(spec)
        with self._lock:
            st = self._shapes.setdefault(key, _ShapeState())
            st.strategy = spec.strategy
            st.runtime_env = spec.runtime_env
            st.queue.append(spec)
        self._pump(key)

    def _pump(self, key):
        """Dispatch queued tasks onto lease capacity; request more leases if
        the queue still has undispatchable work."""
        to_push = []
        new_requests = 0
        with self._lock:
            st = self._shapes.get(key)
            if st is None:
                return
            depth = (self.MAX_INFLIGHT_PER_WORKER
                     if st.requests_in_flight == 0 else 1)
            while st.queue and st.leases:
                lease = min(st.leases, key=lambda l: l.inflight)
                if lease.inflight >= depth:
                    break
                lease.inflight += 1
                to_push.append((lease, st.queue.popleft()))
            new_requests = min(
                max(0, len(st.queue) - st.requests_in_flight),
                self.MAX_LEASES_PER_SHAPE
                - len(st.leases) - st.requests_in_flight)
            if time.monotonic() - st.last_busy < 0.5:
                # the cluster just said it's saturated for this shape:
                # don't storm it with more lease requests; pipelining onto
                # held leases carries the queue meanwhile
                new_requests = 0
            if new_requests > 0:
                st.requests_in_flight += new_requests
        for lease, spec in to_push:
            self._push(key, lease, spec)
        for _ in range(max(0, new_requests)):
            self._lease_pool.submit(self._request_lease, key)

    def _reap_idle_leases(self):
        while not self._stopped.wait(0.25):
            now = time.monotonic()
            to_return = []
            repump = []
            with self._lock:
                for key, st in self._shapes.items():
                    for lease in list(st.leases):
                        if (lease.inflight == 0 and not st.queue
                                and now - lease.idle_since
                                > self.IDLE_LEASE_TTL_S):
                            st.leases.remove(lease)
                            to_return.append(lease)
                    # starvation guard: a queued shape with no outstanding
                    # lease requests re-pumps here — the busy-damping above
                    # deliberately drops requests, and nothing else re-arms
                    # a shape that holds zero leases
                    if st.queue and st.requests_in_flight == 0:
                        repump.append(key)
            for lease in to_return:
                self._return_lease(lease)
            for key in repump:
                self._pump(key)

    def _request_lease(self, key):
        resources, pg_id, bundle_index = dict(key[0]), key[1], key[2]
        agent_addr = self._rt.agent_addr
        cfg = get_config()
        granted = None
        with self._lock:
            st0 = self._shapes.get(key)
            strategy = st0.strategy if st0 else None
            runtime_env = st0.runtime_env if st0 else None
        max_hops = 4
        try:
            if pg_id is not None:
                # PG bundles live on specific nodes; lease at the agent holding
                # the (committed) bundle (ref: the raylet lease request carries
                # the bundle id and the GCS placed it, bundle_spec.h)
                agent_addr = self._resolve_pg_agent(pg_id, bundle_index) or agent_addr
            elif strategy is not None and not isinstance(strategy, DefaultStrategy):
                # constrained strategies pick the node up front (the caller-side
                # analog of the reference's scheduling policies, scheduling/policy/)
                picked = self._pick_strategy_node(resources, strategy)
                if picked is None:
                    # infeasible right now: do NOT fall back to an arbitrary
                    # node — wait and let the pump retry the pick
                    time.sleep(0.2)
                    max_hops = 0
                else:
                    agent_addr = picked
                    max_hops = 1  # do not follow spillback off a constrained node
            for _ in range(max_hops):
                body = {"resources": resources, "timeout": cfg.lease_timeout_s,
                        "job_id": self._rt.job_id.hex(),
                        # lessee identity: if this runtime dies holding the
                        # lease (actor kill, crash), the agent reclaims the
                        # reservation when it reaps our process
                        "lessee": self._rt.worker_id}
                if runtime_env:
                    body["runtime_env"] = runtime_env
                if pg_id is not None:
                    body["pg_id"] = pg_id
                    body["bundle_index"] = bundle_index
                reply = self._rt.peer_pool.get(agent_addr).call(
                    "lease_worker", body, timeout=cfg.lease_timeout_s + 5)
                if reply.get("granted"):
                    granted = _Lease(reply["lease_id"], agent_addr,
                                     tuple(reply["worker_addr"]), reply["worker_id"])
                    break
                if reply.get("redirect"):
                    agent_addr = tuple(reply["redirect"])
                    continue
                if reply.get("busy"):
                    # cluster saturated for this shape right now: back off so
                    # the retry loop doesn't hot-spin, then let _pump decide
                    with self._lock:
                        st_b = self._shapes.get(key)
                        if st_b is not None:
                            st_b.last_busy = time.monotonic()
                    time.sleep(0.1)
                break
        except Exception as e:
            logger.debug("lease request failed: %s", e)
        with self._lock:
            st = self._shapes.get(key)
            if st is None:
                return
            st.requests_in_flight -= 1
            if granted is not None:
                if st.queue:
                    st.leases.append(granted)
                else:
                    self._return_lease(granted)
                    return
        if granted is not None:
            self._pump(key)
        else:
            # failed/busy grant: re-pump whenever work remains — with leases
            # held, the depth gate has just loosened (requests_in_flight
            # dropped), so queued tasks can now pipeline onto them; with no
            # leases at all this retries the lease request (throttled by the
            # busy backoff above)
            with self._lock:
                st = self._shapes.get(key)
                retry = st is not None and bool(st.queue)
            if retry:
                self._pump(key)

    def _pick_strategy_node(self, resources, strategy):
        """Apply spread/affinity/label policies against the control plane's
        cluster view and return the chosen node's agent address."""
        from ray_tpu.core.scheduler import NodeView, pick_node
        try:
            nodes = self._rt.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
        except Exception:
            return None
        views = [NodeView(node_id=n["node_id"], addr=tuple(n["addr"]),
                          total=n["resources"], available=n["available"],
                          labels=n["labels"], alive=n["alive"]) for n in nodes]
        picked = pick_node(views, resources, strategy,
                           local_node_id=self._rt.node_id)
        return picked.addr if picked is not None else None

    def _resolve_pg_agent(self, pg_id, bundle_index):
        """Wait for the PG to be placed, then return the agent address hosting
        the target bundle (first bundle's node when index is -1)."""
        try:
            reply = self._rt.cp_client.call_with_retry(
                "pg_ready", {"pg_id": pg_id, "timeout": 60.0}, timeout=70.0)
            if reply.get("state") != "CREATED":
                return None
            node_ids = reply["node_ids"]
            node_id = node_ids[bundle_index if bundle_index >= 0 else 0]
            return self._rt._node_addr(node_id)
        except Exception:
            return None

    def _push(self, key, lease: _Lease, spec: TaskSpec):
        """(ref: PushNormalTask normal_task_submitter.cc:183)"""
        client = self._rt.peer_pool.get(lease.worker_addr)

        def on_reply(ok, body):
            if ok:
                self._rt.process_task_reply(spec, body)
                self._on_worker_idle(key, lease)
            else:
                self._on_push_failed(key, lease, spec, body)

        client.call_async("push_task", {"spec": spec}, callback=on_reply)

    def _on_worker_idle(self, key, lease: _Lease):
        """(ref: OnWorkerIdle normal_task_submitter.cc:139). A fully idle
        lease is NOT returned here — it lingers IDLE_LEASE_TTL_S (reaper
        thread) so sync call-loops reuse the warm worker."""
        next_spec = None
        repump = False
        with self._lock:
            st = self._shapes.get(key)
            if st is None:
                self._return_lease(lease)
                return
            lease.inflight -= 1
            if lease not in st.leases:
                # _on_push_failed declared this worker dead while other
                # pipelined calls were still in flight: never dispatch onto
                # it again (it would burn a retry on a known-dead address)
                repump = bool(st.queue)
            elif st.queue:
                lease.inflight += 1
                next_spec = st.queue.popleft()
            elif lease.inflight == 0:
                lease.idle_since = time.monotonic()
        if next_spec is not None:
            self._push(key, lease, next_spec)
        elif repump:
            self._pump(key)

    def _on_push_failed(self, key, lease: _Lease, spec: TaskSpec, err):
        with self._lock:
            st = self._shapes.get(key)
            if st is not None and lease in st.leases:
                st.leases.remove(lease)
        self._rt.peer_pool.invalidate(lease.worker_addr)
        retry_spec = self._rt.task_manager.should_retry_system_failure(spec.task_id)
        if retry_spec is not None:
            logger.info("retrying task %s after worker failure (%s)",
                        spec.repr_name(), err)
            self.submit(retry_spec)
        else:
            self._rt.fail_task(spec, TaskError(
                WorkerCrashedError(f"worker at {lease.worker_addr} died: {err}"),
                task_repr=spec.repr_name()))
        self._pump(key)

    def _return_lease(self, lease: _Lease):
        try:
            self._rt.peer_pool.get(lease.agent_addr).notify(
                "return_lease", {"lease_id": lease.lease_id})
        except Exception:
            pass

    def shutdown(self):
        self._stopped.set()
        # Return only IDLE leases so agents free those workers promptly.
        # Leases with pushed tasks still in flight must NOT be returned: the
        # agent would mark the worker free and could re-lease a CPU that is
        # still executing the orphaned task — those are left to the agent's
        # dead-lessee reclamation, which terminates the mid-task worker.
        with self._lock:
            idle = [l for st in self._shapes.values() for l in st.leases
                    if l.inflight == 0]
            for st in self._shapes.values():
                st.leases.clear()
        for lease in idle:
            self._return_lease(lease)
        self._lease_pool.shutdown(wait=False)


@dataclass
class _ActorState:
    actor_id: ActorID
    addr: tuple | None = None
    state: str = "RESOLVING"  # RESOLVING | ALIVE | DEAD
    seq: int = 0
    queued: deque = field(default_factory=deque)       # waiting for address
    inflight: dict = field(default_factory=dict)        # seq -> spec
    death_cause: str = ""
    resolving: bool = False


class ActorTaskSubmitter:
    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._actors: dict[ActorID, _ActorState] = {}
        self._resolve_pool = ThreadPoolExecutor(max_workers=4, thread_name_prefix="actor-resolve")

    def _state(self, actor_id: ActorID) -> _ActorState:
        st = self._actors.get(actor_id)
        if st is None:
            st = self._actors[actor_id] = _ActorState(actor_id)
        return st

    def submit(self, spec: TaskSpec):
        send_to = None
        dead_cause = None
        with self._lock:
            st = self._state(spec.actor_id)
            spec.seq_no = st.seq
            st.seq += 1
            if st.state == "DEAD":
                dead_cause = st.death_cause
            elif st.state == "ALIVE" and st.addr is not None:
                st.inflight[spec.seq_no] = spec
                send_to = st.addr
            else:
                st.queued.append(spec)
                if not st.resolving:
                    st.resolving = True
                    self._resolve_pool.submit(self._resolve, spec.actor_id)
        # _send outside the lock: a synchronous connect failure invokes the
        # on_reply callback inline, and _on_connection_lost takes self._lock
        if send_to is not None:
            self._send(st, send_to, spec)
        elif dead_cause is not None:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor is dead: {dead_cause}"), task_repr=spec.repr_name()))

    def _send(self, st: _ActorState, addr, spec: TaskSpec):
        client = self._rt.peer_pool.get(addr)

        def on_reply(ok, body):
            if ok:
                with self._lock:
                    st.inflight.pop(spec.seq_no, None)
                self._rt.process_task_reply(spec, body)
            else:
                self._on_connection_lost(spec.actor_id, addr, str(body))

        client.call_async("push_task", {"spec": spec}, callback=on_reply)

    def _resolve(self, actor_id: ActorID):
        """Resolve the actor address from the control plane, then flush the
        queue (ref: actor_task_submitter.cc ConnectActor)."""
        try:
            reply = self._rt.cp_client.call_with_retry(
                "resolve_actor", {"actor_id": actor_id, "timeout": 120.0}, timeout=130.0)
        except Exception as e:
            reply = {"state": "DEAD", "death_cause": f"resolve failed: {e}"}
        to_send, to_fail = [], []
        with self._lock:
            st = self._state(actor_id)
            st.resolving = False
            if reply.get("state") == "ALIVE":
                st.state = "ALIVE"
                st.addr = tuple(reply["addr"])
                self._rt.subscribe_actor_events(actor_id)
                # A (re)started actor instance expects sequence numbers from 0:
                # renumber the queue in submission order (the reference tracks
                # this as the caller's per-incarnation sequence window).
                st.seq = 0
                while st.queued:
                    spec = st.queued.popleft()
                    spec.seq_no = st.seq
                    st.seq += 1
                    st.inflight[spec.seq_no] = spec
                    to_send.append((st.addr, spec))
            else:
                st.state = "DEAD"
                st.death_cause = reply.get("death_cause", reply.get("state", "unknown"))
                while st.queued:
                    to_fail.append(st.queued.popleft())
                inflight = list(st.inflight.values())
                st.inflight.clear()
                to_fail.extend(inflight)
        for addr, spec in to_send:
            self._send(self._actors[actor_id], addr, spec)
        for spec in to_fail:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor is dead: {self._actors[actor_id].death_cause}"),
                task_repr=spec.repr_name()))

    def _on_connection_lost(self, actor_id: ActorID, addr, err: str):
        """Push failed: the actor may be restarting. Re-resolve and resubmit
        in-flight tasks whose retry budget allows (ref: actor_task_submitter.cc
        DisconnectActor + retry queue)."""
        with self._lock:
            st = self._state(actor_id)
            if st.addr == addr:
                st.addr = None
                st.state = "RESOLVING"
            self._rt.peer_pool.invalidate(addr)
            inflight = sorted(st.inflight.items())
            st.inflight.clear()
            requeue, fail = [], []
            for _, spec in inflight:
                retry = self._rt.task_manager.should_retry_system_failure(spec.task_id)
                if retry is not None:
                    requeue.append(retry)
                else:
                    fail.append(spec)
            for spec in reversed(requeue):
                st.queued.appendleft(spec)
            if not st.resolving:
                st.resolving = True
                self._resolve_pool.submit(self._resolve, actor_id)
        for spec in fail:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor connection lost: {err}"), task_repr=spec.repr_name()))

    def on_actor_death(self, actor_id: ActorID, reason: str):
        """Pubsub death notification from the control plane."""
        to_fail = []
        with self._lock:
            st = self._actors.get(actor_id)
            if st is None:
                return
            st.state = "DEAD"
            st.death_cause = reason
            st.addr = None
            while st.queued:
                to_fail.append(st.queued.popleft())
            to_fail.extend(st.inflight.values())
            st.inflight.clear()
        for spec in to_fail:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor died: {reason}"), task_repr=spec.repr_name()))

    def on_actor_restart(self, actor_id: ActorID):
        with self._lock:
            st = self._actors.get(actor_id)
            if st is None:
                return
            st.addr = None
            st.state = "RESOLVING"
            if not st.resolving:
                st.resolving = True
                self._resolve_pool.submit(self._resolve, actor_id)

    def shutdown(self):
        self._resolve_pool.shutdown(wait=False)
