"""User-defined metrics: Counter / Gauge / Histogram.

TPU-native analog of the reference's ray.util.metrics
(/root/reference/python/ray/util/metrics.py — Counter:165, Histogram:232,
Gauge:310). Metrics are recorded locally and pushed to the control-plane KV
under "metrics:" keys on flush; a Prometheus-style exposition dump is
available via `collect_prometheus()` (the reference exports through the
dashboard agent → Prometheus pipeline, §5.5)."""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}
        _registry_add(self)

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}

    def set_default_tags(self, tags: dict) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[dict]) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        unknown = set(merged) - set(self._tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys {unknown} for {self._name}")
        return tuple(merged.get(k, "") for k in self._tag_keys)


class Counter(Metric):
    def inc(self, value: float = 1.0, tags: Optional[dict] = None) -> None:
        if value <= 0:
            raise ValueError("counter increments must be positive")
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def _kind(self):
        return "counter"


class Gauge(Metric):
    def set(self, value: float, tags: Optional[dict] = None) -> None:
        with self._lock:
            self._values[self._tag_tuple(tags)] = float(value)

    def _kind(self):
        return "gauge"


class Histogram(Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = list(boundaries or [0.01, 0.1, 1, 10, 100])
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, tags: Optional[dict] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self._boundaries) + 1))
            idx = 0
            while idx < len(self._boundaries) and value > self._boundaries[idx]:
                idx += 1
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def _kind(self):
        return "histogram"


_registry: list[Metric] = []
_registry_lock = threading.Lock()


def _registry_add(metric: Metric) -> None:
    with _registry_lock:
        _registry.append(metric)


def collect_prometheus() -> str:
    """Prometheus text exposition of all registered metrics."""
    lines = []
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        kind = m._kind()
        lines.append(f"# HELP {m._name} {m._description}")
        lines.append(f"# TYPE {m._name} {kind}")
        if isinstance(m, Histogram):
            for key, counts in m._counts.items():
                tags = _fmt_tags(m._tag_keys, key)
                cum = 0
                for b, c in zip(m._boundaries, counts):
                    cum += c
                    lines.append(
                        f'{m._name}_bucket{{le="{b}"{tags}}} {cum}')
                cum += counts[-1]
                lines.append(f'{m._name}_bucket{{le="+Inf"{tags}}} {cum}')
                lines.append(f"{m._name}_sum{{{tags.lstrip(',')}}} "
                             f"{m._sums[key]}")
                lines.append(f"{m._name}_count{{{tags.lstrip(',')}}} "
                             f"{m._totals[key]}")
        else:
            for key, val in m._values.items():
                tags = _fmt_tags(m._tag_keys, key)
                suffix = f"{{{tags.lstrip(',')}}}" if tags else ""
                lines.append(f"{m._name}{suffix} {val}")
    return "\n".join(lines) + "\n"


def _fmt_tags(keys: tuple, values: tuple) -> str:
    if not keys:
        return ""
    return "," + ",".join(f'{k}="{v}"' for k, v in zip(keys, values))


def push_to_control_plane() -> None:
    """Snapshot all metrics into the cluster KV (metrics:<worker>)."""
    from ray_tpu.core import api
    rt = api._try_get_runtime()
    if rt is None:
        return
    payload = collect_prometheus()
    rt.cp_client.notify("kv_put", {
        "key": f"metrics:{rt.worker_id.hex()}",
        "value": payload.encode(), "overwrite": True})
