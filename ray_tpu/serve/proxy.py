"""HTTP ingress proxy.

TPU-native analog of the reference's proxy
(/root/reference/python/ray/serve/_private/proxy.py — HTTPProxy:706,
proxy_request:414, send_request_to_replica:886): an aiohttp server that
resolves the route prefix to an application's ingress deployment, routes via
the pow-2 router, and returns the replica's response. JSON in/out; the
reference's full ASGI passthrough is out of scope for the HTTP layer v1 —
deployments see a dict request body.
"""

from __future__ import annotations

import asyncio
import contextvars
import json
import threading
from typing import Optional

import ray_tpu
from ray_tpu.observability import tracing
from ray_tpu.serve.router import Router

_SSE_DONE = object()  # sentinel: streaming generator exhausted


class HTTPProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self.host = host
        self.port = port
        self._routers: dict[str, Router] = {}
        self._http_dispatch: dict[tuple, bool] = {}
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._runner = None

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._serve_thread,
                                        daemon=True, name="http_proxy")
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("http proxy failed to start")

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _serve_thread(self):
        from concurrent.futures import ThreadPoolExecutor

        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        # Blocking calls (router.assign, ray_tpu.get for the whole
        # generation) run on the loop's default executor. Its stdlib default
        # is min(32, cpus+4) threads — ~5 on a small host — which silently
        # caps proxy concurrency far below the replicas' batch capacity.
        loop.set_default_executor(
            ThreadPoolExecutor(max_workers=128, thread_name_prefix="proxy-io"))
        self._loop = loop

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        if self.port == 0:  # OS-assigned: report the real port
            for s in site._server.sockets:
                self.port = s.getsockname()[1]
                break
        self._runner = runner
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(runner.cleanup())
            loop.close()

    # ---- request path --------------------------------------------------
    async def _resolve_route(self, path: str):
        routes = await _aget(self._controller.get_http_routes.remote())
        best = None
        for prefix, target in routes.items():
            if prefix is None:
                continue
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info.get("tail", "")
        if path == "/-/routes":
            routes = await _aget(self._controller.get_http_routes.remote())
            return web.json_response(
                {p: f"{a}#{d}" for p, (a, d) in routes.items()})
        if path == "/-/healthz":
            return web.Response(text="ok")

        resolved = await self._resolve_route(path)
        if resolved is None:
            return web.Response(status=404, text=f"no route for {path}")
        prefix, (app_name, deployment) = resolved

        router = self._routers.get(app_name)
        if router is None:
            router = Router(self._controller, app_name)
            self._routers[app_name] = router

        # build the request payload the user callable sees
        body = await request.read()
        payload: object
        if body:
            try:
                payload = json.loads(body)
            except json.JSONDecodeError:
                payload = body
        else:
            payload = dict(request.query)

        # Ingresses that define handle_http(path, method, payload) get the
        # sub-path dispatched to them (OpenAI-style multi-route apps,
        # ray_tpu.serve.llm.openai_api); plain callables get __call__.
        subpath = path[len(prefix.rstrip("/")):] or "/"
        loop = asyncio.get_event_loop()
        try:
            # root span of the whole Serve request: the assign below runs
            # on an executor thread, which does NOT inherit this
            # coroutine's contextvars — copy_context() carries the span
            # across so the replica call stitches into this trace
            with tracing.span(f"http.request:{path}", kind="server",
                              attrs={"method": request.method,
                                     "app": app_name,
                                     "deployment": deployment}):
                wants_dispatch = await loop.run_in_executor(
                    None, self._wants_http_dispatch, app_name, deployment)
                # SSE only for multi-route (handle_http) ingresses that opt
                # in via the OpenAI-style "stream" field — a plain
                # deployment whose payload happens to contain stream=true
                # keeps json responses
                streaming = (wants_dispatch and isinstance(payload, dict)
                             and bool(payload.get("stream")))
                if wants_dispatch:
                    call = (deployment, "handle_http",
                            (subpath, request.method, payload))
                else:
                    call = (deployment, "__call__", (payload,))
                pctx = contextvars.copy_context()
                ref = await loop.run_in_executor(
                    None, lambda: pctx.run(
                        router.assign, call[0], call[1], call[2], {},
                        streaming=streaming))
                if streaming and hasattr(ref, "__next__"):
                    # ObjectRefGenerator: stream each chunk to the client
                    # the moment the replica yields it (SSE framing;
                    # reference: proxy ASGI streaming). First byte goes out
                    # at first token, not at completion. Once the response
                    # is prepared, errors must be delivered IN-STREAM (an
                    # SSE error event + [DONE]) — aiohttp cannot start a
                    # second response.
                    resp = web.StreamResponse(
                        headers={"Content-Type": "text/event-stream",
                                 "Cache-Control": "no-cache"})
                    await resp.prepare(request)
                    gen = iter(ref)

                    def _next_chunk():
                        try:
                            # bounded: a hung replica must not pin an
                            # executor thread (and this connection) forever
                            return ray_tpu.get(next(gen), timeout=120.0)
                        except StopIteration:
                            return _SSE_DONE

                    try:
                        while True:
                            chunk = await loop.run_in_executor(
                                None, _next_chunk)
                            if chunk is _SSE_DONE:
                                break
                            data = json.dumps(chunk) \
                                if not isinstance(chunk, str) else chunk
                            await resp.write(f"data: {data}\n\n".encode())
                    except (ConnectionResetError, asyncio.CancelledError):
                        raise  # client went away: nothing left to tell it
                    except Exception as e:  # noqa: BLE001 — stream error
                        await resp.write(
                            b"data: " + json.dumps(
                                {"error": {"message": repr(e)}}).encode()
                            + b"\n\n")
                    await resp.write(b"data: [DONE]\n\n")
                    await resp.write_eof()
                    return resp
                result = await _aget(ref)
        except TimeoutError as e:
            return web.Response(status=503, text=str(e))
        except Exception as e:  # noqa: BLE001 - surface replica errors as 500
            return web.Response(status=500, text=repr(e))

        if streaming and isinstance(result, list):
            # server-sent events framing (legacy list-returning replicas)
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream",
                         "Cache-Control": "no-cache"})
            await resp.prepare(request)
            for chunk in result:
                data = json.dumps(chunk) if not isinstance(chunk, str) \
                    else chunk
                await resp.write(f"data: {data}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp
        if isinstance(result, (bytes, bytearray)):
            return web.Response(body=bytes(result))
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)

    def _wants_http_dispatch(self, app_name: str, deployment: str) -> bool:
        """Does the ingress deployment define handle_http? (cached; the
        controller records the flag at deploy time)."""
        key = (app_name, deployment)
        cached = self._http_dispatch.get(key)
        if cached is None:
            try:
                cached = bool(ray_tpu.get(
                    self._controller.ingress_has_http_dispatch.remote(
                        app_name, deployment), timeout=5.0))
            except Exception:  # noqa: BLE001 - older controller: plain calls
                cached = False
            self._http_dispatch[key] = cached
        return cached


async def _aget(ref):
    loop = asyncio.get_event_loop()
    return await loop.run_in_executor(None, lambda: ray_tpu.get(ref))
