"""Request deadline propagation.

End-to-end deadline carrier for the Serve request path (and any other
caller that opts in): the ingress derives an ABSOLUTE wall-clock deadline
(``time.time()`` epoch seconds — it must survive process hops on the same
host, which ``time.monotonic()`` does not) and every layer below bounds
its own waits by the REMAINING budget instead of hardcoded constants.

Same carrier pattern as distributed tracing (observability/tracing.py):
the value lives in a contextvar; ``core.worker`` injects it into
``TaskSpec.deadline`` at submit and re-establishes the contextvar around
task/actor-task execution, so a deadline set at the proxy reaches the
replica, the batcher, and the LLM engine without any signature changes.
The design follows Dean & Barroso, "The Tail at Scale" (CACM 2013):
refuse to *start* expired work, bound every wait by what's left, and
cancel on expiry rather than computing answers nobody will read.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional

from ray_tpu.exceptions import DeadlineExceededError

_deadline: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "ray_tpu_request_deadline", default=None)


def current() -> Optional[float]:
    """The ambient absolute deadline (epoch seconds), or None."""
    return _deadline.get()


def remaining(default: Optional[float] = None) -> Optional[float]:
    """Seconds left on the ambient deadline (can be <= 0), or `default`
    when no deadline is set."""
    d = _deadline.get()
    if d is None:
        return default
    return d - time.time()


def expired() -> bool:
    d = _deadline.get()
    return d is not None and time.time() >= d


def bound(timeout: Optional[float]) -> Optional[float]:
    """Clamp a wait to the remaining deadline budget.

    Returns min(timeout, remaining) — with either side allowed to be
    None (no bound from that side). A non-positive result is floored at a
    tiny epsilon so downstream waits fail fast with their own timeout
    error instead of blocking for a default."""
    rem = remaining()
    if rem is None:
        return timeout
    if timeout is None or rem < timeout:
        timeout = rem
    return max(timeout, 0.001)


def raise_if_expired(what: str = "request") -> None:
    """Admission check: refuse to start work whose deadline has passed."""
    d = _deadline.get()
    if d is not None and time.time() >= d:
        raise DeadlineExceededError(
            f"{what} deadline exceeded {time.time() - d:.3f}s ago")


@contextlib.contextmanager
def scope(deadline: Optional[float]) -> Iterator[Optional[float]]:
    """Establish `deadline` as the ambient deadline for the block.

    ``scope(None)`` is a no-op passthrough (keeps any outer deadline), so
    executors can wrap unconditionally with ``spec.deadline``."""
    if deadline is None:
        yield _deadline.get()
        return
    token = _deadline.set(deadline)
    try:
        yield deadline
    finally:
        _deadline.reset(token)
