"""graftlint — AST-based concurrency/JAX-hygiene analysis for ray_tpu.

A self-contained static-analysis framework (pure ``ast``, no imports of
analyzed code, JAX-free) with a pass registry, an intraprocedural
lock-context/call-graph model, and a committed findings baseline that
the tier-1 suite enforces. See ``ray_tpu/analysis/core.py`` for the
design notes and pragma syntax, README "Static analysis" for the pass
catalogue, and ``ray-tpu lint`` for the CLI.

Passes (package sweep): lock-discipline, rpc-ack, host-sync,
jit-hygiene, unbounded-growth. Tests-scoped: tier1-marks (the migrated
tier-1 drift guard).
"""

from ray_tpu.analysis.baseline import (baseline_path, diff as baseline_diff,
                                       load as load_baseline,
                                       save as save_baseline)
from ray_tpu.analysis.core import (Finding, ModuleSource, Pass, all_passes,
                                   default_passes, package_dir, register,
                                   repo_root, run_passes)

__all__ = [
    "Finding", "ModuleSource", "Pass", "all_passes", "default_passes",
    "register", "run_passes", "package_dir", "repo_root",
    "baseline_path", "baseline_diff", "load_baseline", "save_baseline",
]
