"""LLMServer: the serve deployment wrapping the continuous-batching engine.

Matches the reference's LLMServer deployment
(python/ray/llm/_internal/serve/deployments/llm/llm_server.py): one engine
per replica, requests routed by serve's pow-2 router, OpenAI-shaped request
and response dicts. Streaming uses generator endpoints (drained through the
engine's per-request token queues).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Iterator, Optional

from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.engine import LLMEngine

# Engine stats as one tagged gauge family through the util.metrics
# registry + flusher pipeline (delta reports into the CP time-series
# store — the legacy `metrics:<worker>` KV blob path is gone).
# Module-level singleton: the metrics registry is per-process and a
# replica restart in the same worker must not register a duplicate
# family. Phase/compile/ITL histograms are NOT re-exported here — the
# engine's profiler records those into their own metric families
# (observability/profiling.py); this family carries the scalar
# counters/gauges, including the profiler-derived scalars below.
_ENGINE_GAUGE = None
_EXPORTED_STATS = (
    "steps", "prefills", "tokens_out", "requests", "shed_expired",
    "active_slots", "waiting", "prefilling", "free_pages",
    "prefix_hits", "prefix_misses", "prefix_hit_tokens",
    "prefix_hit_pages", "prefix_cached_pages", "prefix_evictable_pages",
    "prefix_shared_pages", "prefix_evictions", "prefix_inserted_pages",
    "decode_block_effective", "pending_pipeline_depth",
    # tiered KV cache (ISSUE 7): spill/restore economy + per-tier bytes
    "spilled_pages", "restored_pages", "tier_hit_tokens",
    "tier_bytes_shm", "tier_bytes_disk",
    # prefix-affinity routing (ISSUE 10): tier-hint prefetch economy +
    # the summary the router sees (version/pages exported to the CP)
    "tier_prefetch_hints", "tier_prefetch_pages", "tier_prefetch_hit_pages",
    "prefix_summary_version", "prefix_summary_pages",
    "spec_rounds", "spec_drafted_tokens", "spec_accepted_tokens",
    # mid-stream failover (ISSUE 14): continuations admitted + tokens of
    # dead-replica work recovered without recompute (prefix + tier pages)
    "failover_resumed", "failover_restored_tokens",
    # fleet disagg (ISSUE 16): remote-prefill handoffs restored here +
    # their encoded wire bytes and decode-overlapped restore milliseconds
    "disagg_prefills", "handoff_bytes_wire", "handoff_overlap_ms",
    # elastic fleet (ISSUE 17): cache-warm scale-up restore economy
    "warm_start_pages", "warm_start_ms",
    # paged-attention kernel family (ISSUE 18): resolved backend (string
    # — exported as a one-hot stat tag; numeric twin alongside) + per-
    # kernel compile/dispatch counters, so a fleet mixing gather/pallas
    # replicas is visible in `ray-tpu` status and on the dashboard
    "attention_backend", "attn_backend_pallas", "attn_kernel_compiles",
    "attn_decode_dispatches", "attn_verify_dispatches",
    "attn_chunk_dispatches",
    # tensor parallelism (ISSUE 20): sharding degree + mesh shape (string
    # — one-hot export like attention_backend) and one chip's slice of
    # the KV pool in bytes (page counts elsewhere stay whole-replica)
    "tp_degree", "mesh_shape", "kv_shard_pool_bytes",
    "kv_shard_page_occupancy",
    # introspection scalars (ISSUE 6): compile tracker + memory gauges;
    # None-valued entries (no samples yet / cpu backend) are skipped
    "compile_events", "mid_traffic_compiles", "compile_s",
    "weights_bytes", "kv_pool_bytes", "kv_page_occupancy",
    "device_bytes_in_use", "device_peak_bytes", "itl_s")


def _export_engine_stats(model_id: str, stats: dict) -> None:
    """Record engine counters as registry gauges and flush (best-effort:
    benches/tests run engines with no runtime up)."""
    global _ENGINE_GAUGE
    try:
        from ray_tpu.core import api
        from ray_tpu.util import metrics
        if _ENGINE_GAUGE is None:
            _ENGINE_GAUGE = metrics.Gauge(
                "ray_tpu_llm_engine",
                "LLM engine counters (incl. prefix-cache hit/miss/evict)",
                tag_keys=("model", "replica", "stat"))
        rt = api._try_get_runtime()
        replica = rt.worker_id.hex()[:8] if rt is not None else "local"
        for key in _EXPORTED_STATS:
            val = stats.get(key)
            if val is None:
                continue
            if isinstance(val, str):
                # string-valued stats (attention_backend) export as a
                # one-hot gauge keyed "stat:value" — a float() here would
                # raise and silently drop every later key's export
                _ENGINE_GAUGE.set(
                    1.0, tags={"model": model_id, "replica": replica,
                               "stat": f"{key}:{val}"})
                continue
            _ENGINE_GAUGE.set(
                float(val),
                tags={"model": model_id, "replica": replica,
                      "stat": key})
        # immediate flush (not the 10s interval): dashboards scrape engine
        # gauges right after probing stats, so they must be current
        metrics.flush_now()
    except Exception:  # noqa: BLE001 — observability must not fail serving
        pass


def _resume_plan(resume_tokens, resume_count, cfg: LLMConfig):
    """Decide how a re-dispatched stream resumes: `(use_continuation,
    skip)`. Continuation admits prompt+resume through the cache-aware
    path and emits only new tokens. Past `failover_max_resumes` (or with
    failover off) the request degrades to a plain retry-from-scratch:
    regenerate everything and suppress the first `skip` tokens so the
    spliced client stream still carries no duplicates (greedy regenerates
    the identical prefix)."""
    n = len(resume_tokens or ())
    if not n:
        return False, 0
    if cfg.failover_enabled and int(resume_count or 0) <= \
            cfg.failover_max_resumes:
        return True, 0
    return False, n


def _chat_prompt(messages: list[dict]) -> str:
    """Minimal chat template (role-tagged concatenation)."""
    parts = []
    for m in messages:
        parts.append(f"<|{m.get('role', 'user')}|>{m.get('content', '')}")
    parts.append("<|assistant|>")
    return "".join(parts)


class LLMServer:
    """Deployment callable. Each replica owns one engine (and therefore the
    TPU chips of its placement bundle — one engine process per chip group,
    SURVEY.md §7 hard-part 7)."""

    def __init__(self, llm_config: LLMConfig | dict):
        if isinstance(llm_config, dict):
            llm_config = LLMConfig(**llm_config)
        self.cfg = llm_config
        self.engine = LLMEngine(llm_config)
        self.engine.start()
        # Eager in-flight spill on SIGTERM (ISSUE 14): a graceful kill
        # pushes every live chain's computed pages into the KV tier
        # before the process dies, so the failover continuation restores
        # instead of re-prefilling. Best-effort: actors run handlers off
        # the main thread (ValueError) and tests embed servers in-process.
        try:
            import signal

            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                try:
                    self.eager_spill()
                finally:
                    if callable(prev):
                        prev(signum, frame)

            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError, RuntimeError):
            pass

    # ---- OpenAI-shaped endpoints --------------------------------------
    def completions(self, payload: dict) -> Any:
        prompt = payload.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        params = self._sampling(payload)
        if payload.get("stream"):
            return self._stream_completion(prompt, params, chat=False,
                                           resume=self._resume_spec(payload))
        out = self.engine.generate(prompt, **params)
        return self._completion_response(out, chat=False)

    def chat(self, payload: dict) -> Any:
        prompt = _chat_prompt(payload.get("messages", []))
        params = self._sampling(payload)
        if payload.get("stream"):
            return self._stream_completion(prompt, params, chat=True,
                                           resume=self._resume_spec(payload))
        out = self.engine.generate(prompt, **params)
        return self._completion_response(out, chat=True)

    def models(self) -> dict:
        return {"object": "list",
                "data": [{"id": self.cfg.model_id, "object": "model",
                          "owned_by": "ray_tpu"}]}

    # ---- plumbing ------------------------------------------------------
    def _sampling(self, payload: dict) -> dict:
        out = {}
        if payload.get("max_tokens") is not None:
            out["max_tokens"] = int(payload["max_tokens"])
        if payload.get("temperature") is not None:
            out["temperature"] = float(payload["temperature"])
        if payload.get("top_k") is not None:
            out["top_k"] = int(payload["top_k"])
        # Fleet disagg handoff marker (ISSUE 16): the proxy already ran
        # the remote prefill and the chain is registered in the tier —
        # the engine's ordinary restore path IS the handoff; the flag
        # only routes the restore's accounting to the disagg counters.
        if payload.get("_disagg_handoff"):
            out["disagg"] = True
        # Ingress page-chain digests (ISSUE 10): the proxy computed them
        # once for routing; the replica carries them request-scoped
        # (serve/replica.py set the contextvar before dispatch) and the
        # engine reuses them for its tier restore after a page-0 check.
        from ray_tpu.serve import affinity
        digests = affinity.get_request_prefix_digests()
        if digests:
            out["prefix_digests"] = digests
        # Proxy-assigned X-Request-Id (ISSUE 12): reuse it as the engine
        # request id so the exemplar/timeline and client logs correlate.
        from ray_tpu.observability import attribution
        rid = attribution.get_request_id()
        if rid:
            out["request_id"] = rid
        return out

    @staticmethod
    def _resume_spec(payload: dict):
        """Continuation spec from a proxy re-dispatch (ISSUE 14): token
        ids already streamed to the client + how many resumes this
        request has burned. None for ordinary first-leg requests."""
        toks = payload.get("resume_tokens")
        if not toks:
            return None
        return ([int(t) for t in toks], int(payload.get("resume_count", 1)))

    def _completion_response(self, out: dict, chat: bool) -> dict:
        oid = f"cmpl-{uuid.uuid4().hex[:24]}"
        if chat:
            choice = {"index": 0, "finish_reason": "stop",
                      "message": {"role": "assistant", "content": out["text"]}}
            obj = "chat.completion"
        else:
            choice = {"index": 0, "finish_reason": "stop",
                      "text": out["text"]}
            obj = "text_completion"
        return {
            "id": oid, "object": obj, "created": int(time.time()),
            "model": self.cfg.model_id, "choices": [choice],
            "usage": {
                "prompt_tokens": out.get("num_prompt_tokens", 0),
                "completion_tokens": out.get("num_generated_tokens", 0),
                "total_tokens": out.get("num_prompt_tokens", 0)
                + out.get("num_generated_tokens", 0),
            },
            # engine-side timing + critical-path attribution (the bench
            # harness and the proxy's SLO finalizer read these)
            "ray_tpu": {"ttft_s": out.get("ttft_s"),
                        "latency_s": out.get("latency_s"),
                        "queue_wait_s": out.get("queue_wait_s"),
                        "request_id": out.get("request_id"),
                        "stages": out.get("stages") or []},
        }

    async def _stream_completion(self, prompt: str, params: dict, chat: bool,
                                 resume=None):
        """Async generator of OpenAI stream chunks (SSE payloads minus
        framing). Async so the poll sleep yields the replica's event loop —
        N streaming requests drain concurrently instead of serializing.

        `resume` (ISSUE 14) is a proxy continuation spec
        `(token_ids, resume_count)`: within the resume cap the request is
        admitted as prompt+tokens through the cache-aware path and emits
        only post-resume tokens; past the cap it degrades to a plain
        retry-from-scratch with the already-streamed prefix suppressed.
        Every delta chunk carries `token_ids` (the proxy's emitted-token
        journal — text deltas alone are not token-identifiable) and the
        first chunk of a resumed leg carries restore accounting for the
        proxy's `failover` attribution stage."""
        import asyncio

        import time as _time

        t0 = _time.monotonic()
        n_prompt = len(self.engine.tokenizer.encode(prompt)) \
            if isinstance(prompt, str) else len(prompt)
        resume_tokens, resume_count = resume if resume else ([], 0)
        use_resume, skip = _resume_plan(resume_tokens, resume_count, self.cfg)
        if use_resume:
            rid = self.engine.submit(prompt, resume_tokens=resume_tokens,
                                     **params)
        elif skip:
            # retry-from-scratch: the caller sent the REMAINING budget, so
            # restore the original cap — the suppressed regenerated prefix
            # must not eat into the tokens still owed to the client
            p2 = dict(params)
            if p2.get("max_tokens") is not None:
                p2["max_tokens"] = int(p2["max_tokens"]) + skip
            rid = self.engine.submit(prompt, **p2)
        else:
            rid = self.engine.submit(prompt, **params)
        oid = f"cmpl-{uuid.uuid4().hex[:24]}"
        obj = "chat.completion.chunk" if chat else "text_completion"
        ntok = 0
        ttft = None
        resume_meta_due = resume is not None
        try:
            while True:
                d = self.engine.drain(rid)
                # gate on TOKENS, not decoded text: a tokenizer can decode
                # a batch to "" (byte tokenizer on unprintable ids) and the
                # stream must still emit the chunk — TTFT is first-token
                # time
                toks = list(d.get("tokens") or ())
                text = d.get("text", "")
                if toks and skip:
                    drop = min(skip, len(toks))
                    skip -= drop
                    toks = toks[drop:]
                    text = self.engine.tokenizer.decode(toks) if toks else ""
                if toks:
                    if ttft is None:
                        ttft = _time.monotonic() - t0
                    ntok += len(toks)
                    if chat:
                        delta = {"delta": {"content": text}, "index": 0,
                                 "finish_reason": None}
                    else:
                        delta = {"text": text, "index": 0,
                                 "finish_reason": None}
                    chunk = {"id": oid, "object": obj,
                             "model": self.cfg.model_id, "choices": [delta],
                             "token_ids": toks}
                    if resume_meta_due:
                        resume_meta_due = False
                        prog = self.engine.request_progress(rid) or {}
                        chunk["resume_meta"] = {
                            "resumed": use_resume,
                            "restored_tokens": prog.get("restored_tokens", 0),
                            "restore_bytes": prog.get("restore_bytes", 0),
                            "restore_ms": prog.get("restore_ms", 0.0),
                            "cached_tokens": prog.get("cached_tokens", 0)}
                    yield chunk
                if d["done"]:
                    err = d.get("error")
                    reason = "error" if err else "stop"
                    fin = ({"delta": {}, "index": 0, "finish_reason": reason}
                           if chat else
                           {"text": "", "index": 0, "finish_reason": reason})
                    # final chunk carries usage + engine-side timing so
                    # streaming clients (and the bench) get the same
                    # accounting as the non-streaming path
                    final = {"id": oid, "object": obj,
                             "model": self.cfg.model_id, "choices": [fin],
                             "usage": {"prompt_tokens": n_prompt,
                                       "completion_tokens": ntok,
                                       "total_tokens": n_prompt + ntok},
                             "ray_tpu": {"ttft_s": ttft,
                                         "latency_s":
                                         _time.monotonic() - t0,
                                         "queue_wait_s":
                                         d.get("queue_wait_s"),
                                         "request_id": d.get("request_id"),
                                         "stages": d.get("stages") or []}}
                    if err:
                        final["error"] = {"message": str(err)}
                    yield final
                    return
                await asyncio.sleep(0.01)
        finally:
            # abandoned stream (client disconnect -> generator close): stop
            # burning batch slots and reap the engine entry — nothing will
            # drain it again
            self.engine.cancel(rid)

    # raw engine access (bench, composition)
    def generate(self, prompt: str, **kw) -> dict:
        return self.engine.generate(prompt, **kw)

    def submit(self, prompt: str, **kw) -> str:
        return self.engine.submit(prompt, **kw)

    def drain(self, request_id: str) -> dict:
        return self.engine.drain(request_id)

    def engine_stats(self) -> dict:
        stats = self.engine.engine_stats()
        _export_engine_stats(self.cfg.model_id, stats)
        return stats

    def warm_start(self, max_bytes: Optional[int] = None,
                   budget_s: Optional[float] = None) -> dict:
        """Cache-warm scale-up hook (ISSUE 17): the controller calls this
        through `handle_request` after readiness but BEFORE publishing
        the replica into the routing table. Restores the fleet's hottest
        tier chains into the local prefix cache under the configured
        byte/time budgets; {"supported": False, "pages": 0} when the KV
        tier or warm start is off (the controller then publishes
        immediately — same unsupported idiom as prefix_summary)."""
        return self.engine.warm_start(max_bytes=max_bytes,
                                      budget_s=budget_s)

    def eager_spill(self) -> dict:
        """Drain/SIGTERM hook (ISSUE 14): spill every in-flight chain's
        computed pages into the KV tier NOW, so continuations on
        surviving replicas restore this replica's work instead of
        recomputing it. No-op (0 pages) when the tier is off."""
        return {"spilled_pages": self.engine.spill_inflight()}

    # ---- prefix-affinity routing (ISSUE 10) ---------------------------
    def prefix_summary(self, since: Optional[int] = None) -> dict:
        """Bounded summary of this replica's resident prefix chains, for
        the controller's summary collector. `since` is the version the
        caller already holds — an unchanged index answers with a tiny
        "unchanged" marker instead of re-shipping the digest list.
        {"supported": False} permanently when the prefix cache is off."""
        snap = self.engine.prefix_summary(self.cfg.prefix_summary_max_pages)
        if snap is None:
            return {"supported": False}
        version, digests = snap
        meta = {
            "tokenizer": self.cfg.tokenizer,
            "page_size": self.cfg.page_size,
            "max_prompt_len": self.cfg.max_prompt_len,
            "kv_tier": bool(self.cfg.kv_tier_enabled
                            and self.cfg.prefix_cache_enabled),
            "model_id": self.cfg.model_id,
            # fleet disagg placement inputs (ISSUE 16): the router's
            # disagg_plan reads these off rs.meta — which prefill pool
            # serves this deployment and past how many estimated
            # prefill tokens the handoff pays
            "disagg_prefill": self.cfg.disagg_prefill_deployment,
            "disagg_prompt_threshold": int(
                self.cfg.disagg_prompt_threshold or 0),
        }
        if since is not None and int(since) == version:
            return {"supported": True, "version": version,
                    "unchanged": True, "meta": meta}
        return {"supported": True, "version": version, "meta": meta,
                "digests": digests}

    def prefetch_hint(self, digests: list) -> dict:
        """Router's tier-hint: start fetching the non-resident tail of
        this chain from the KV tier now, overlapping admission."""
        return self.engine.prefetch_hint(digests)

    def check_health(self) -> bool:
        # periodic health checks double as the metrics heartbeat: every
        # probe refreshes this replica's engine gauges on the CP
        _export_engine_stats(self.cfg.model_id, self.engine.engine_stats())
        return True

    # ---- HTTP ingress dispatch (proxy calls handle_http when defined) --
    def handle_http(self, path: str, method: str, payload: Any) -> Any:
        path = "/" + path.strip("/")
        if path.endswith("/chat/completions"):
            return self.chat(payload if isinstance(payload, dict) else {})
        if path.endswith("/completions"):
            return self.completions(
                payload if isinstance(payload, dict) else {})
        if path.endswith("/models"):
            return self.models()
        if path.endswith("/stats"):
            return self.engine_stats()
        return {"error": {"message": f"no route for {path}", "code": 404}}


def build_llm_deployment(llm_config: LLMConfig, *, name: Optional[str] = None):
    """LLMServer as a serve Deployment (one engine per replica). TPU
    placement comes from llm_config.ray_actor_options (e.g.
    {"resources": {"TPU": 4}}) — each replica then lands on a TPU worker
    process owning those chips."""
    from ray_tpu import serve

    return serve.deployment(
        LLMServer,
        name=name or llm_config.name,
        num_replicas=llm_config.num_replicas,
        max_ongoing_requests=4 * llm_config.max_batch_size,
        ray_actor_options=dict(llm_config.ray_actor_options or {}),
        slo_ttft_p99_ms=llm_config.slo_ttft_p99_ms,
        slo_e2e_p99_ms=llm_config.slo_e2e_p99_ms,
        slo_sample_rate=llm_config.slo_sample_rate,
        # first requests compile XLA programs for minutes on TPU; don't let
        # routine health checking kill the replica mid-compile
        health_check_timeout_s=600.0,
    )
