"""Power-of-two-choices request router with retries and ejection.

TPU-native analog of the reference's router
(/root/reference/python/ray/serve/_private/router.py — AsyncioRouter:457,
assign_request:838; request_router/pow_2_router.py): pick two random
replicas, probe cached queue lengths, route to the shorter queue. Queue
lengths are refreshed in the background; routing table updates come from the
controller via versioned polls (the reference uses long-poll, long_poll.py).

Robustness layer (Dean & Barroso, "The Tail at Scale", CACM 2013):

- `call()` retries replica-fault failures (dead/unreachable replica — never
  user exceptions) on a different replica, gated by a Finagle-style
  RetryBudget so retries stay bounded at ~10% of traffic instead of
  storming a degraded cluster.
- Consecutive failures eject a replica from routing (circuit breaker);
  after a cooldown it must pass a health probe before taking traffic again.
- Every wait is bounded by the ambient request deadline
  (core/deadline.py); expired requests are refused before a replica is
  picked.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu.core import deadline as request_deadline
from ray_tpu.observability import attribution
from ray_tpu.observability import events as _fr
from ray_tpu.util import metrics as _metrics
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                DeadlineExceededError, GetTimeoutError,
                                NodeDiedError, ObjectLostError, TaskError,
                                WorkerCrashedError)
from ray_tpu.serve.config import RouterConfig

# fault classes meaning "the REPLICA is broken, the request may be fine":
# safe to retry elsewhere. User exceptions and deadline/timeout errors are
# excluded — retrying those wastes budget on work that will fail again.
# ObjectLostError counts: the reply object died with the replica's node, so
# the outcome is unusable and re-execution elsewhere is the recovery.
_REPLICA_FAULTS = (ActorDiedError, ActorUnavailableError, WorkerCrashedError,
                   NodeDiedError, ObjectLostError)

# Built-in router metrics (ISSUE 4): flushed to the CP time-series store by
# the hosting process's MetricsFlusher.
_RETRY_SPEND = _metrics.Counter(
    "ray_tpu_serve_router_retries_total",
    "retry-budget spend: requests retried on another replica",
    tag_keys=("deployment",))
_EJECTION_COUNTER = _metrics.Counter(
    "ray_tpu_serve_router_ejections_total",
    "replicas ejected from routing by the circuit breaker",
    tag_keys=("deployment",))
# Prefix-affinity routing (ISSUE 10): hit = routed to a resident-prefix
# holder; spillover = best holder saturated, demoted to pow-2; stale
# fallback = summaries too old / router degraded, demoted to pow-2.
_AFFINITY_HITS = _metrics.Counter(
    "ray_tpu_serve_router_affinity_hits_total",
    "requests routed to a replica holding their resident prefix",
    tag_keys=("deployment",))
_AFFINITY_SPILLOVERS = _metrics.Counter(
    "ray_tpu_serve_router_affinity_spillovers_total",
    "affinity demotions because every useful holder was saturated",
    tag_keys=("deployment",))
_AFFINITY_STALE = _metrics.Counter(
    "ray_tpu_serve_router_affinity_stale_fallbacks_total",
    "affinity demotions because summaries were stale or the router "
    "was degraded",
    tag_keys=("deployment",))
_AFFINITY_MATCHED_PAGES = _metrics.Histogram(
    "ray_tpu_serve_router_affinity_matched_pages",
    "resident prefix pages matched on affinity-routed requests",
    boundaries=[1, 2, 4, 8, 16, 32, 64],
    tag_keys=("deployment",))


def is_replica_fault(exc: BaseException) -> bool:
    if isinstance(exc, _REPLICA_FAULTS):
        return True
    if isinstance(exc, TaskError):
        return isinstance(exc.cause, _REPLICA_FAULTS)
    return False


class RetryBudget:
    """Token bucket bounding retries to a fraction of request volume
    (Finagle's RetryBudget): each request deposits `ratio` tokens, each
    retry withdraws 1.0, balance capped at `cap`. Thread-safe."""

    def __init__(self, ratio: float = 0.1, cap: float = 10.0):
        self._ratio = ratio
        self._cap = cap
        self._balance = cap  # start full: a cold router may retry
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self._cap, self._balance + self._ratio)

    def withdraw(self) -> bool:
        with self._lock:
            if self._balance >= 1.0:
                self._balance -= 1.0
                return True
            return False

    def balance(self) -> float:
        with self._lock:
            return self._balance


class ReplicaSet:
    """Cached view of one deployment's replicas + queue lengths + per-replica
    circuit-breaker state (keyed by actor id, so state survives routing-table
    refreshes that rebuild the handle list)."""

    def __init__(self, config: Optional[RouterConfig] = None,
                 name: str = ""):
        self.config = config or RouterConfig()
        self.name = name                   # deployment (metric tag)
        self.replicas: list = []           # actor handles
        self.version: int = -1
        # probe cache keyed by STABLE replica identity (actor id hex), not
        # list index: a routing-table refresh reshuffles indices, and an
        # index-keyed cache would attribute one replica's queue length to
        # another for up to queue_len_staleness_s
        self._qlen: dict[str, tuple[float, int]] = {}  # key -> (ts, len)
        # circuit breaker, keyed by actor id hex
        self._fails: dict[str, int] = {}          # consecutive failures
        self._ejected: dict[str, float] = {}      # key -> ejected-at ts
        self._cb_lock = threading.Lock()
        self.ejections = 0
        self.readmissions = 0
        # ---- prefix-affinity state (ISSUE 10) --------------------------
        # per-replica resident-prefix summaries shipped by the controller
        # through the routing long-poll: key -> frozenset of page-chain
        # digest hex strings
        self._summaries: dict[str, frozenset] = {}
        self.summary_gen: int = -1   # controller's summary generation
        self.meta: dict = {}         # deployment affinity meta (tokenizer,
        #                              page_size, kv_tier, ...)
        # last time a long-poll cycle against the controller SUCCEEDED
        # (whether or not it shipped new summaries): choose() treats
        # summaries as stale once this ages past affinity_summary_ttl_s —
        # a wedged controller must not steer traffic on a frozen view
        self.summaries_ok_at: float = 0.0
        # router-level degraded flag mirrored here so choose() can demote
        # affinity the moment the control plane goes away
        self.degraded = False
        self.affinity_hits = 0
        self.affinity_spillovers = 0
        self.affinity_stale_fallbacks = 0

    @staticmethod
    def _key(replica) -> str:
        aid = getattr(replica, "_actor_id", None)
        return aid.hex() if hasattr(aid, "hex") else str(id(replica))

    def update(self, replicas: list, version: int):
        self.replicas = replicas
        self.version = version
        live = {self._key(r) for r in replicas}
        # identity-keyed probe entries stay valid across a table refresh;
        # only entries for departed replicas are dropped
        self._qlen = {k: v for k, v in self._qlen.items() if k in live}
        # a replaced replica must start cold: its predecessor's summary
        # (same deployment slot, different actor) does not carry over
        self._summaries = {k: v for k, v in self._summaries.items()
                           if k in live}
        with self._cb_lock:
            # controller replaced dead replicas: drop breaker state for
            # handles that no longer route
            self._fails = {k: v for k, v in self._fails.items() if k in live}
            self._ejected = {k: v for k, v in self._ejected.items()
                             if k in live}

    def apply_summaries(self, gen: int, meta: dict,
                        summaries: dict[str, list]) -> None:
        """Install controller-shipped prefix summaries (long-poll path).

        `summaries` maps replica key -> list of resident page-chain digest
        hex strings. The payload is the deployment's FULL summary state:
        entries absent from it are removed now (the replica reported
        nothing resident or stopped answering probes), and entries for
        replicas outside the current table never route (choose() walks the
        routable set)."""
        self.summary_gen = gen
        self.meta = dict(meta or {})
        live = {self._key(r) for r in self.replicas}
        self._summaries = {key: frozenset(digs)
                           for key, digs in (summaries or {}).items()
                           if key in live}

    # ---- circuit breaker ------------------------------------------------
    def record_success(self, replica) -> None:
        with self._cb_lock:
            self._fails.pop(self._key(replica), None)

    def record_failure(self, replica) -> bool:
        """Count a replica-fault failure; returns True if this ejected the
        replica from routing. Always poisons the queue-length probe cache
        for the replica: a fresh pre-fault probe can make a dead replica
        look idle for up to queue_len_staleness_s, and a mid-stream
        failover redispatch must land on a SURVIVOR on the first try, not
        spend a retry-budget token rediscovering the corpse."""
        key = self._key(replica)
        ejected = False
        with self._cb_lock:
            n = self._fails.get(key, 0) + 1
            self._fails[key] = n
            self._qlen[key] = (time.monotonic(), self._QLEN_DEAD)
            if n >= self.config.ejection_threshold \
                    and key not in self._ejected:
                self._ejected[key] = time.monotonic()
                self.ejections += 1
                ejected = True
        if ejected:
            # journal outside the breaker lock — emit is a queue push,
            # but nothing on the routing path waits on it
            _fr.emit("replica_ejected", "WARNING",
                     deployment=self.name, replica=key,
                     reason=f"{n} consecutive replica faults",
                     attrs={"threshold":
                            int(self.config.ejection_threshold)})
        return ejected

    def _routable(self) -> list:
        """(replica, key) pairs not currently ejected; cooled-down ejectees
        are health probed and readmitted when they pass (re-armed when they
        don't). The identity key rides along so selection never rescans
        self.replicas to recover it."""
        now = time.monotonic()
        out = []
        for r in self.replicas:
            key = self._key(r)
            with self._cb_lock:
                ejected_at = self._ejected.get(key)
            if ejected_at is None:
                out.append((r, key))
                continue
            if now - ejected_at < self.config.ejection_cooldown_s:
                continue
            # cooldown over: one synchronous health probe decides (bounded
            # by the ambient deadline — readmission must not burn the
            # caller's remaining budget)
            try:
                ray_tpu.get(r.check_health.remote(),
                            timeout=request_deadline.bound(
                                self.config.health_probe_timeout_s))
                ok = True
            except Exception:  # noqa: BLE001 — still broken
                ok = False
            with self._cb_lock:
                if ok:
                    self._ejected.pop(key, None)
                    self._fails.pop(key, None)
                    self.readmissions += 1
                else:
                    self._ejected[key] = time.monotonic()  # re-arm cooldown
            if ok:
                _fr.emit("replica_readmitted", "INFO",
                         deployment=self.name, replica=key,
                         reason="health probe passed after cooldown")
                out.append((r, key))
        return out

    # ---- selection ------------------------------------------------------
    _QLEN_DEAD = 1 << 30  # probe-failed sentinel: replica looks infinitely busy

    def _probe(self, replica, key: str) -> int:
        now = time.monotonic()
        cached = self._qlen.get(key)
        if cached and now - cached[0] < self.config.queue_len_staleness_s:
            return cached[1]
        try:
            # bounded by the ambient deadline too: probing a dead replica
            # must not burn the caller's remaining budget
            qlen = ray_tpu.get(replica.get_queue_len.remote(),
                               timeout=request_deadline.bound(
                                   self.config.queue_probe_timeout_s))
        except Exception as e:  # noqa: BLE001 - dead replica looks busy
            qlen = self._QLEN_DEAD
            if is_replica_fault(e):
                # a probe that died with an actor fault is the same
                # signal as a failed call: charge the breaker so a corpse
                # is eventually EJECTED instead of re-probed (one probe
                # timeout burned) every staleness window forever. A plain
                # probe timeout is NOT charged — a busy-but-alive replica
                # must only look busy, never accrue toward ejection.
                self.record_failure(replica)
        self._qlen[key] = (now, qlen)
        return qlen

    def _match_len(self, digests: list, resident: frozenset) -> int:
        """Longest LEADING run of request digests resident on a replica.
        Chain digests commit to the whole prefix, so a broken run past the
        first miss cannot be reused by match_prefix — stop there."""
        n = 0
        for d in digests:
            if d not in resident:
                break
            n += 1
        return n

    def best_match(self, digests: Optional[list]) -> int:
        """Best leading-prefix match (pages) across ALL summaries — the
        disagg threshold's estimate of how much prefill the decode pool
        already holds for this request, regardless of which replica the
        pick lands on. 0 when summaries are stale/absent (no evidence =
        assume cold, which only errs toward the prefill pool on long
        prompts — exactly the requests the pool exists for)."""
        if not digests or not self._summaries_usable():
            return 0
        best = 0
        for resident in self._summaries.values():
            if resident:
                best = max(best, self._match_len(digests, resident))
        return best

    def _summaries_usable(self) -> bool:
        if self.degraded:
            return False
        ttl = self.config.affinity_summary_ttl_s
        return (self.summaries_ok_at > 0.0
                and time.monotonic() - self.summaries_ok_at < ttl)

    def _pow2(self, candidates: list):
        """Power-of-two-choices over (replica, key) pairs."""
        n = len(candidates)
        if n == 1:
            return candidates[0][0]
        i, j = random.sample(range(n), 2)
        (ri, ki), (rj, kj) = candidates[i], candidates[j]
        qi, qj = self._probe(ri, ki), self._probe(rj, kj)
        if min(qi, qj) < self._QLEN_DEAD:
            return ri if qi <= qj else rj
        # both sampled candidates look dead (a node just died): fall back
        # to a full scan — any live replica beats two dead ones
        best, best_q = ri, qi
        for c, key in candidates:
            q = self._probe(c, key)
            if q < best_q:
                best, best_q = c, q
        return best

    def choose(self, model_id: str = "",
               prefix_digests: Optional[list] = None) -> Optional[object]:
        return self.choose_info(model_id, prefix_digests)[0]

    def choose_info(self, model_id: str = "",
                    prefix_digests: Optional[list] = None) -> tuple:
        """Pick a replica; returns (replica | None, matched_prefix_pages).

        Selection order: multiplexed rendezvous (model cache affinity
        outranks prefix affinity), then prefix affinity when the request
        carries digests and fresh summaries name a non-saturated holder,
        else pow-2. matched_prefix_pages is 0 on every non-affinity path —
        the caller uses it to decide whether a tier prefetch hint is worth
        sending."""
        candidates = self._routable()
        n = len(candidates)
        if n == 0:
            return None, 0
        if model_id:
            # multiplexed request: rendezvous-hash affinity keeps the model's
            # per-replica cache hot (serve/multiplex.py)
            from ray_tpu.serve.multiplex import rendezvous_pick
            reps = [r for r, _ in candidates]
            return reps[rendezvous_pick(reps, model_id)], 0
        if (prefix_digests and self.config.affinity_enabled
                and self._summaries):
            if not self._summaries_usable():
                self.affinity_stale_fallbacks += 1
                _AFFINITY_STALE.inc(tags={"deployment": self.name})
                attribution.note(demotion="stale_summaries")
                return self._pow2(candidates), 0
            scored = []
            for r, key in candidates:
                resident = self._summaries.get(key)
                if not resident:
                    continue
                m = self._match_len(prefix_digests, resident)
                if m >= self.config.affinity_min_match_pages:
                    scored.append((m, r, key))
            if scored:
                # load × locality (ISSUE 14 satellite): each holder's
                # matched pages are discounted by its EXCESS queue depth
                # over the least-loaded routable replica — score =
                # matched − w·(q − q_min). Continuous, so equal holders
                # split by live load instead of the old binary
                # affinity_spillover_qlen threshold letting the top
                # holder absorb everything until saturation. Probes are
                # cached (queue_len_staleness_s), so the q_min scan costs
                # at most one probe sweep per staleness window.
                qlens = {key: self._probe(r, key) for r, key in candidates}
                q_min = min(qlens.values())
                w = self.config.affinity_load_weight
                scored.sort(key=lambda t: t[0], reverse=True)
                best, best_score = None, 0.0
                for m, r, key in scored:
                    s = m - w * (qlens[key] - q_min)
                    if s > best_score:
                        best, best_score = (m, r, key), s
                if best is not None:
                    m, r, key = best
                    self.affinity_hits += 1
                    _AFFINITY_HITS.inc(tags={"deployment": self.name})
                    _AFFINITY_MATCHED_PAGES.observe(
                        m, tags={"deployment": self.name})
                    return r, m
                # no holder's locality survives its load: demote to pow-2
                # (an idle non-holder beats every loaded holder)
                self.affinity_spillovers += 1
                _AFFINITY_SPILLOVERS.inc(tags={"deployment": self.name})
                attribution.note(demotion="spillover")
        return self._pow2(candidates), 0


class Router:
    """Routes requests for any deployment in one application.

    Config updates arrive by LONG-POLL push from the controller (reference
    long_poll.py): a background thread hangs on poll_routing_table and
    applies changes the moment versions bump — the request path reads only
    the local cache, no controller RPC per request."""

    def __init__(self, controller, app_name: str,
                 config: Optional[RouterConfig] = None):
        self._controller = controller
        self._app = app_name
        self.config = config or RouterConfig()
        self._sets: dict[str, ReplicaSet] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._budget = RetryBudget(self.config.retry_budget_ratio,
                                   self.config.retry_budget_cap)
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "retries": 0, "retries_denied": 0,
                      "deadline_exceeded": 0}
        # DEGRADED mode (tentpole b): the controller (or the CP under it)
        # is unreachable, so the router keeps serving from its cached
        # routing tables instead of failing requests. Flag + since-ts are
        # surfaced via stats_snapshot for the proxy /-/stats and tests.
        self._degraded = False
        self._degraded_since: Optional[float] = None
        self._poll_thread = threading.Thread(
            target=self._long_poll_loop, name=f"router-poll-{app_name}",
            daemon=True)
        self._poll_thread.start()

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _set_degraded(self, degraded: bool) -> None:
        with self._stats_lock:
            if degraded and not self._degraded:
                self._degraded = True
                self._degraded_since = time.monotonic()
            elif not degraded and self._degraded:
                self._degraded = False
                self._degraded_since = None
        # mirror into every replica set: affinity must demote to pow-2 the
        # moment the control plane goes away, not a summary-TTL later
        with self._lock:
            for rs in self._sets.values():
                rs.degraded = degraded

    def stats_snapshot(self) -> dict:
        with self._stats_lock:
            out = dict(self.stats)
            out["degraded"] = self._degraded
            out["degraded_for_s"] = (
                time.monotonic() - self._degraded_since
                if self._degraded_since is not None else 0.0)
        out["retry_budget"] = self._budget.balance()
        with self._lock:
            out["ejections"] = sum(rs.ejections for rs in self._sets.values())
            out["readmissions"] = sum(rs.readmissions
                                      for rs in self._sets.values())
            out["affinity_hits"] = sum(rs.affinity_hits
                                       for rs in self._sets.values())
            out["affinity_spillovers"] = sum(
                rs.affinity_spillovers for rs in self._sets.values())
            out["affinity_stale_fallbacks"] = sum(
                rs.affinity_stale_fallbacks for rs in self._sets.values())
        return out

    def _apply_table(self, table: dict) -> None:
        with self._lock:
            for dep, entry in table.items():
                # entries are (replicas, version) or, from controllers that
                # collect prefix summaries, (replicas, version, summary)
                # where summary = {"gen", "meta", "replicas"} or None
                # (= unchanged since the gen we acknowledged)
                replicas, version = entry[0], entry[1]
                summary = entry[2] if len(entry) > 2 else None
                cur = self._sets.setdefault(dep,
                                            ReplicaSet(self.config, dep))
                cur.degraded = self._degraded
                # version monotonicity (ISSUE 17): a stale table delivered
                # late — a cold-start get_routing_table racing the
                # long-poll — must not regress the replica set. Applying
                # it could resurrect a replica the controller already
                # flipped out for retirement, or show a pre-publish view
                # missing a freshly warmed one. Version 0 passes: a fresh
                # controller's rebuilt deployment starts there, and the
                # router's set for a deleted deployment is dropped below
                # before any rebuild is seen.
                if 0 < version < cur.version:
                    continue
                if version != cur.version:
                    cur.update(replicas, version)
                if summary is not None:
                    # after update(): apply_summaries filters against the
                    # replica set the summaries describe
                    cur.apply_summaries(summary.get("gen", 0),
                                        summary.get("meta") or {},
                                        summary.get("replicas") or {})
            # the table is the app's FULL routing state: deployments that
            # were deleted must drop out of the cache, or the long-poll
            # version handshake never converges
            for dep in [d for d, rs in self._sets.items()
                        if d not in table and rs.version >= 0]:
                del self._sets[dep]

    def _mark_summaries_ok(self) -> None:
        now = time.monotonic()
        with self._lock:
            for rs in self._sets.values():
                rs.summaries_ok_at = now

    def _long_poll_loop(self) -> None:
        while not self._stopped.is_set():
            with self._lock:
                # [table_version, summary_gen] handshake: the controller
                # re-ships a deployment when EITHER moves (older
                # controllers that expect bare ints still work — they
                # compare unequal and ship a full 2-tuple entry)
                known = {d: [rs.version, rs.summary_gen]
                         for d, rs in self._sets.items()}
            try:
                table = ray_tpu.get(
                    self._controller.poll_routing_table.remote(
                        self._app, known, 30.0), timeout=40.0)
            except Exception:  # noqa: BLE001 - controller/CP briefly away:
                # DEGRADED — keep routing from the cached tables; requests
                # must not fail just because the control plane blinked
                self._set_degraded(True)
                time.sleep(0.5)
                continue
            self._set_degraded(False)
            if table:
                self._apply_table(table)
            # a completed poll round (even an empty timeout) proves the
            # controller is alive: its summaries are as fresh as they get
            self._mark_summaries_ok()

    def stop(self) -> None:
        self._stopped.set()

    def affinity_meta(self, deployment: str) -> dict:
        """Deployment affinity meta (tokenizer/page_size/...) shipped with
        its summaries; {} until summaries have arrived — the proxy then
        skips digest computation entirely."""
        with self._lock:
            rs = self._sets.get(deployment)
            return dict(rs.meta) if rs is not None and rs.meta else {}

    def disagg_plan(self, deployment: str,
                    prefix_digests: Optional[list],
                    prompt_tokens: int) -> Optional[dict]:
        """Third placement mode (ISSUE 16): decide whether this request
        should prefill on the deployment's paired prefill pool before
        its decode dispatch. Returns None for the ordinary colocated
        path, else ``{"prefill_deployment", "est_prefill_tokens"}``.

        The estimate is the prompt length minus what the decode pool
        already holds resident (best leading match across summaries ×
        page_size): a long prompt whose prefix is hot decodes colocated
        — the handoff only pays for COLD prefill FLOPs."""
        with self._lock:
            rs = self._sets.get(deployment)
        if rs is None or not rs.meta:
            return None
        prefill_dep = rs.meta.get("disagg_prefill")
        threshold = int(rs.meta.get("disagg_prompt_threshold") or 0)
        if not prefill_dep or threshold <= 0 or prompt_tokens <= 0:
            return None
        page_size = int(rs.meta.get("page_size") or 0)
        est = max(0, int(prompt_tokens)
                  - rs.best_match(prefix_digests) * page_size)
        if est <= threshold:
            return None
        return {"prefill_deployment": str(prefill_dep),
                "est_prefill_tokens": est}

    def _maybe_refresh(self, deployment: str, force: bool = False):
        with self._lock:
            rs = self._sets.setdefault(
                deployment, ReplicaSet(self.config, deployment))
            if rs.replicas and not force:
                return rs
        # cold start / forced: one synchronous fetch. During a controller /
        # CP outage this fails — serve from whatever table we already have
        # (degraded) rather than failing the request.
        try:
            table = ray_tpu.get(self._controller.get_routing_table.remote(
                self._app), timeout=10.0)
        except Exception:  # noqa: BLE001 — degraded: cached table stands
            self._set_degraded(True)
        else:
            self._set_degraded(False)
            self._apply_table(table)
            self._mark_summaries_ok()
        with self._lock:
            return self._sets.setdefault(
                deployment, ReplicaSet(self.config, deployment))

    def _pick(self, deployment: str, multiplexed_model_id: str,
              timeout_s: float, prefix_digests: Optional[list] = None):
        """Block until a routable replica exists (bounded by `timeout_s`
        AND the ambient deadline). Returns (replica_set, replica,
        matched_prefix_pages)."""
        wait_until = time.monotonic() \
            + request_deadline.bound(timeout_s)
        while True:
            request_deadline.raise_if_expired("request")
            rs = self._maybe_refresh(deployment)
            replica, matched = rs.choose_info(multiplexed_model_id,
                                              prefix_digests)
            if replica is not None:
                return rs, replica, matched
            if time.monotonic() > wait_until:
                raise TimeoutError(
                    f"no replicas available for deployment "
                    f"{deployment!r} after {timeout_s}s")
            self._maybe_refresh(deployment, force=True)
            time.sleep(0.1)

    def _maybe_prefetch(self, rs: ReplicaSet, replica, matched: int,
                        prefix_digests: Optional[list]) -> None:
        """Tier prefetch hint: the chosen replica does not hold the whole
        requested prefix resident, so tell it NOW which chain is coming —
        its KV-tier lookup/fetch then overlaps request transfer + queueing
        instead of serializing inside engine._admit. Data-plane RPC to the
        replica itself: the request path stays free of controller/CP
        calls."""
        if (not prefix_digests or not self.config.prefetch_hints_enabled
                or matched >= len(prefix_digests)
                or not rs.meta.get("kv_tier")):
            return
        try:
            replica.handle_request.remote(  # graftlint: fire-and-forget — best-effort warmup; the request itself is the fallback path
                "prefetch_hint", (list(prefix_digests),), {})
        except Exception:  # noqa: BLE001 — hint is pure opportunism
            pass

    def assign(self, deployment: str, method: str, args: tuple,
               kwargs: dict, *, streaming: bool = False,
               timeout_s: float = 30.0, multiplexed_model_id: str = "",
               prefix_digests: Optional[list] = None):
        """Pick a replica and submit; returns the reply ObjectRef.

        No retries — the caller owns the ref (DeploymentHandle path).
        `call()` is the retrying variant for request/response traffic."""
        return self.assign_info(
            deployment, method, args, kwargs, streaming=streaming,
            timeout_s=timeout_s, multiplexed_model_id=multiplexed_model_id,
            prefix_digests=prefix_digests)[0]

    def assign_info(self, deployment: str, method: str, args: tuple,
                    kwargs: dict, *, streaming: bool = False,
                    timeout_s: float = 30.0, multiplexed_model_id: str = "",
                    prefix_digests: Optional[list] = None) -> tuple:
        """`assign` returning (ref, replica): callers that own the stream
        (the proxy's SSE path) need the replica handle to charge the
        circuit breaker when the stream dies mid-flight (ISSUE 14)."""
        t_route = time.time()
        rs, replica, matched = self._pick(deployment, multiplexed_model_id,
                                          timeout_s, prefix_digests)
        self._maybe_prefetch(rs, replica, matched, prefix_digests)
        if streaming:
            # streaming-generator call: returns an ObjectRefGenerator
            # whose items land as the replica yields them
            ref = replica.handle_request_streaming.options(
                num_returns="streaming").remote(method, args, kwargs)
        else:
            ref = replica.handle_request.remote(method, args, kwargs)
        # route stage = pick (probe/affinity score) + queue-handoff submit;
        # the end is the moment the replica actor owns the request
        attribution.note(replica=rs._key(replica)[:12], matched_pages=matched)
        attribution.stamp("route", t_route, time.time())
        return ref, replica

    # ---- streaming retry-budget accounting (ISSUE 14 satellite) ---------
    # Streaming requests never pass through call(), so a mostly-SSE fleet
    # used to neither fund nor spend the retry budget: the proxy deposits
    # when a stream COMPLETES and withdraws for each mid-stream
    # re-dispatch (failover continuation or retry-from-scratch).

    def stream_deposit(self) -> None:
        """A stream ran to completion: fund the retry budget, exactly as
        a completed unary call() does."""
        self._bump("requests")
        self._budget.deposit()

    def stream_withdraw(self, deployment: str) -> bool:
        """Spend one retry token for a mid-stream re-dispatch. False =
        budget empty: the caller must fail the stream instead of storming
        a degraded fleet with continuations."""
        if not self._budget.withdraw():
            self._bump("retries_denied")
            return False
        self._bump("retries")
        _RETRY_SPEND.inc(tags={"deployment": deployment})
        return True

    def record_replica_fault(self, deployment: str, replica) -> None:
        """Charge the circuit breaker for a replica fault observed OUTSIDE
        call() (a stream that died mid-flight)."""
        with self._lock:
            rs = self._sets.get(deployment)
        if rs is not None and rs.record_failure(replica):
            _EJECTION_COUNTER.inc(tags={"deployment": deployment})

    def call(self, deployment: str, method: str, args: tuple, kwargs: dict,
             *, timeout_s: Optional[float] = None,
             multiplexed_model_id: str = "",
             prefix_digests: Optional[list] = None) -> tuple:
        """Submit and WAIT for the reply, absorbing replica faults: a
        dead/unreachable replica is recorded against the circuit breaker
        and the request is retried on another replica, gated by the retry
        budget and `max_retries_per_request`. Waits are bounded by the
        ambient deadline. Returns (result, attempts_used).

        Raises the final error when retries are exhausted/denied; user
        exceptions and deadline expiry propagate immediately (retrying
        them would fail again and burn budget)."""
        self._bump("requests")
        self._budget.deposit()
        attempts = 0
        no_replica_timeout = (timeout_s if timeout_s is not None
                              else self.config.no_replica_timeout_s)
        while True:
            try:
                request_deadline.raise_if_expired("request")
            except DeadlineExceededError:
                self._bump("deadline_exceeded")
                raise
            t_route = time.time()
            rs, replica, matched = self._pick(
                deployment, multiplexed_model_id, no_replica_timeout,
                prefix_digests)
            self._maybe_prefetch(rs, replica, matched, prefix_digests)
            ref = replica.handle_request.remote(method, args, kwargs)
            attempts += 1
            # one route stamp per attempt: a retried request shows every
            # pick + handoff in its timeline (sorted canonically)
            attribution.note(replica=rs._key(replica)[:12],
                             matched_pages=matched)
            attribution.stamp("route", t_route, time.time(),
                              attempt=attempts)
            try:
                result = ray_tpu.get(
                    ref, timeout=request_deadline.bound(timeout_s))
                rs.record_success(replica)
                return result, attempts
            except (GetTimeoutError, DeadlineExceededError):
                # the replica may still be healthy — just slow/over-deadline;
                # don't charge the breaker, don't retry (no budget left in
                # the deadline anyway)
                self._bump("deadline_exceeded")
                try:
                    ray_tpu.cancel(ref)  # stop computing an answer nobody reads
                except Exception:  # noqa: BLE001 — best-effort
                    pass
                raise
            except Exception as e:  # noqa: BLE001 — classify below
                if isinstance(e, TaskError) and isinstance(
                        e.cause, DeadlineExceededError):
                    # replica shed it at dequeue: too late to retry
                    self._bump("deadline_exceeded")
                    raise
                if not is_replica_fault(e):
                    rs.record_success(replica)  # replica fine; request isn't
                    raise
                if rs.record_failure(replica):
                    _EJECTION_COUNTER.inc(tags={"deployment": deployment})
                if attempts > self.config.max_retries_per_request:
                    raise
                if not self._budget.withdraw():
                    self._bump("retries_denied")
                    raise
                self._bump("retries")
                _RETRY_SPEND.inc(tags={"deployment": deployment})
                self._maybe_refresh(deployment, force=True)
