"""Scale-envelope stress bench: many nodes / actors / queued tasks / PGs.

Proves the control plane + scheduler survive the reference's published
envelope SHAPE (reference: release/benchmarks/README.md — 250+ nodes, 10k+
actors, 1M queued tasks, 1k PGs on a real cluster; numbers in
release/perf_metrics/benchmarks/many_*.json) at single-box scale: >=50
virtual nodes, >=1,000 actors, >=10,000 queued tasks, >=500 placement
groups, all against ONE control plane.

Workers run IN-PROCESS (threads, not subprocesses — Cluster.add_node
inproc_workers=True, the fake_multi_node-style harness): the box has one
core, so the measurement is control-plane/scheduler capacity, not fork
throughput.

Writes SCALE_BENCH.json and prints one JSON line per section.

Usage: python bench_scale.py [--nodes 50] [--actors 1000] [--tasks 10000]
                             [--pgs 500] [--out SCALE_BENCH.json]
"""

from __future__ import annotations

import argparse
import json
import os
import time

# Many-agent single-host run: stretch periodic control-plane work BEFORE the
# framework loads its config — 50 nodes heartbeating at 1 Hz (each scanning
# /proc for gauges) would eat the single core the workload needs.
os.environ.setdefault("RAY_TPU_AGENT_HEARTBEAT_INTERVAL_S", "10.0")
os.environ.setdefault("RAY_TPU_HEALTH_CHECK_PERIOD_S", "10.0")
os.environ.setdefault("RAY_TPU_HEALTH_CHECK_TIMEOUT_S", "60.0")


def _p(msg: str) -> None:
    print(msg, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=50)
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--tasks", type=int, default=10000)
    ap.add_argument("--pgs", type=int, default=500)
    ap.add_argument("--out", default="SCALE_BENCH.json")
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu.core.cluster import Cluster

    results: dict = {"config": vars(args)}

    # ---- many nodes ----------------------------------------------------
    cpus_per_node = max(1, -(-args.actors // args.nodes))
    t0 = time.monotonic()
    cluster = Cluster()
    for i in range(args.nodes):
        cluster.add_node(num_cpus=cpus_per_node,
                         object_store_memory=8 * 1024 * 1024,
                         inproc_workers=True)
        if (i + 1) % 10 == 0:
            _p(f"... {i + 1}/{args.nodes} nodes up "
               f"({time.monotonic() - t0:.1f}s)")
    ray_tpu.init(address=cluster.address)
    # the CP must see every node alive
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        alive = sum(1 for n in ray_tpu.nodes() if n.get("alive", True))
        if alive >= args.nodes:
            break
        time.sleep(0.5)
    dt = time.monotonic() - t0
    alive = sum(1 for n in ray_tpu.nodes() if n.get("alive", True))
    results["nodes"] = {"target": args.nodes, "alive": alive,
                        "bringup_s": round(dt, 2),
                        "nodes_per_s": round(args.nodes / dt, 1)}
    _p(json.dumps({"section": "nodes", **results["nodes"]}))
    assert alive >= args.nodes, f"only {alive}/{args.nodes} nodes alive"

    # ---- many queued tasks --------------------------------------------
    @ray_tpu.remote
    def nop():
        return None

    t0 = time.monotonic()
    refs = [nop.remote() for _ in range(args.tasks)]
    t_submit = time.monotonic() - t0
    ray_tpu.get(refs, timeout=600.0)
    t_total = time.monotonic() - t0
    results["tasks"] = {
        "count": args.tasks,
        "submit_per_s": round(args.tasks / t_submit, 1),
        "throughput_per_s": round(args.tasks / t_total, 1),
        "wall_s": round(t_total, 2)}
    _p(json.dumps({"section": "tasks", **results["tasks"]}))
    del refs

    # ---- many actors ---------------------------------------------------
    @ray_tpu.remote
    class Sink:
        def ping(self):
            return 1

    t0 = time.monotonic()
    actors = [Sink.options(scheduling_strategy="SPREAD").remote()
              for _ in range(args.actors)]
    # one ping per actor proves every one of them is scheduled + running
    ray_tpu.get([a.ping.remote() for a in actors], timeout=900.0)
    t_up = time.monotonic() - t0
    t0 = time.monotonic()
    ray_tpu.get([a.ping.remote() for a in actors], timeout=900.0)
    t_ping = time.monotonic() - t0
    t0 = time.monotonic()
    for a in actors:
        ray_tpu.kill(a)
    t_kill = time.monotonic() - t0
    results["actors"] = {
        "count": args.actors,
        "create_to_first_ping_per_s": round(args.actors / t_up, 1),
        "steady_ping_per_s": round(args.actors / t_ping, 1),
        "kill_per_s": round(args.actors / t_kill, 1),
        "bringup_s": round(t_up, 2)}
    _p(json.dumps({"section": "actors", **results["actors"]}))
    del actors
    time.sleep(2.0)  # let kill/reap churn drain before the PG section

    # ---- many placement groups ----------------------------------------
    from ray_tpu import placement_group, remove_placement_group

    t0 = time.monotonic()
    pgs = [placement_group([{"CPU": 0.01}]) for _ in range(args.pgs)]
    for pg in pgs:
        pg.ready(timeout=300.0)
    t_create = time.monotonic() - t0
    t0 = time.monotonic()
    for pg in pgs:
        remove_placement_group(pg)
    t_remove = time.monotonic() - t0
    results["pgs"] = {
        "count": args.pgs,
        "create_per_s": round(args.pgs / t_create, 1),
        "remove_per_s": round(args.pgs / t_remove, 1)}
    _p(json.dumps({"section": "pgs", **results["pgs"]}))

    results["ts"] = time.time()
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    _p(json.dumps({"metric": "scale_envelope",
                      "value": args.actors, "unit": "actors",
                      "ok": True}))

    ray_tpu.shutdown()
    cluster.shutdown()


if __name__ == "__main__":
    main()
