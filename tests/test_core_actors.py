"""Actor tests: creation, ordering, named actors, failure semantics.

Models the reference's python/ray/tests/test_actor.py coverage.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, by=1):
        self.n += by
        return self.n

    def read(self):
        return self.n


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6
    assert ray_tpu.get(c.read.remote()) == 6


def test_actor_constructor_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote()) == 100


def test_actor_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(20)]
    assert ray_tpu.get(refs[-1]) == 20
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_two_actors_isolated(ray_start_regular):
    a, b = Counter.remote(), Counter.remote(10)
    ray_tpu.get([a.inc.remote(), b.inc.remote()])
    assert ray_tpu.get(a.read.remote()) == 1
    assert ray_tpu.get(b.read.remote()) == 11


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def boom(self):
            raise RuntimeError("actor-boom")

        def ok(self):
            return "fine"

    b = Bad.remote()
    with pytest.raises(exceptions.TaskError) as ei:
        ray_tpu.get(b.boom.remote())
    assert "actor-boom" in str(ei.value)
    # actor survives method errors
    assert ray_tpu.get(b.ok.remote()) == "fine"


def test_actor_creation_error(ray_start_regular):
    @ray_tpu.remote
    class FailInit:
        def __init__(self):
            raise RuntimeError("init-boom")

        def m(self):
            return 1

    f = FailInit.remote()
    with pytest.raises(exceptions.TaskError):
        ray_tpu.get(f.m.remote(), timeout=30)


def test_named_actor(ray_start_regular):
    Counter.options(name="counter1").remote(7)
    h = ray_tpu.get_actor("counter1")
    assert ray_tpu.get(h.read.remote()) == 7


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    with pytest.raises((exceptions.TaskError, exceptions.ActorDiedError)):
        ray_tpu.get(c.inc.remote(), timeout=30)


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1, max_task_retries=1)
    class Dying:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    d = Dying.remote()
    assert ray_tpu.get(d.inc.remote()) == 1
    # the kill itself must not be retried on the restarted actor
    d.die.options(max_task_retries=0).remote()
    time.sleep(1.0)
    # state reset after restart; max_task_retries lets the call retry
    assert ray_tpu.get(d.inc.remote(), timeout=60) == 1


def test_actor_handle_passing(ray_start_regular):
    @ray_tpu.remote
    def use_actor(h):
        return ray_tpu.get(h.inc.remote())

    c = Counter.remote()
    assert ray_tpu.get(use_actor.remote(c)) == 1
    assert ray_tpu.get(c.read.remote()) == 1


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class Async:
        async def slow_echo(self, x):
            import asyncio
            await asyncio.sleep(0.1)
            return x

    a = Async.remote()
    refs = [a.slow_echo.remote(i) for i in range(5)]
    start = time.monotonic()
    assert ray_tpu.get(refs, timeout=30) == list(range(5))
    # concurrent execution: 5 * 0.1s awaited concurrently, not serially
    assert time.monotonic() - start < 3.0


def test_exit_actor(ray_start_regular):
    @ray_tpu.remote
    class Quitter:
        def quit(self):
            ray_tpu.exit_actor()
            return "bye"

        def m(self):
            return 1

    q = Quitter.remote()
    assert ray_tpu.get(q.quit.remote(), timeout=30) == "bye"
    time.sleep(0.5)
    with pytest.raises((exceptions.TaskError, exceptions.ActorDiedError)):
        ray_tpu.get(q.m.remote(), timeout=30)
