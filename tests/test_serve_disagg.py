"""Prefill/decode disaggregation tests (reference:
python/ray/llm/_internal/serve/deployments/prefill_decode_disagg/
prefill_decode_disagg.py + its serve tests). Tiny-Llama on CPU."""

import json
import urllib.request

import numpy as np
import pytest

import ray_tpu


def _tiny_cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=64, max_seq_len=128, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


def test_prefill_handoff_matches_monolithic():
    """A prompt prefilled on engine A and decoded on engine B must emit the
    same greedy tokens as one engine doing both — the KV pages really carry
    the prompt state across the handoff."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.disagg import DecodeEngine, prefill_only
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _tiny_cfg(max_tokens=6)
    mc = cfg.llama()
    params = llama.init_params(jax.random.PRNGKey(3), mc)

    mono = LLMEngine(cfg, params=params)
    mono.start()
    want = mono.generate([7, 3, 9, 1, 4] * 4, max_tokens=6,
                         temperature=0.0)["tokens"]
    mono.shutdown()

    pre = LLMEngine(cfg, params=params)       # prefill role: loop NOT started
    dec = DecodeEngine(cfg, params=params)    # decode role
    dec.start()
    try:
        state = prefill_only(pre, [7, 3, 9, 1, 4] * 4, temperature=0.0)
        assert state["plen"] == 20
        assert state["kv_k"].shape[2] == state["n_pages"]
        rid = dec.submit_prefilled(state, max_tokens=6)
        got = dec.result(rid, timeout=120.0)
        assert got["error"] is None
        assert got["tokens"] == want
        # pages recycled on both sides
        assert pre.engine_stats()["free_pages"] == cfg.num_pages - 1
    finally:
        dec.shutdown()


def test_disagg_decode_concurrency_and_page_recycling():
    """Several prefilled requests stream through one decode engine; slots
    and pages fully recycle."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.disagg import DecodeEngine, prefill_only
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _tiny_cfg(max_batch_size=2, num_pages=32, max_tokens=5)
    mc = cfg.llama()
    params = llama.init_params(jax.random.PRNGKey(5), mc)
    pre = LLMEngine(cfg, params=params)
    dec = DecodeEngine(cfg, params=params)
    dec.start()
    try:
        rids = []
        for i in range(5):
            state = prefill_only(pre, [i + 1] * 8, temperature=0.0)
            rids.append(dec.submit_prefilled(state, max_tokens=5))
        outs = [dec.result(r, timeout=120.0) for r in rids]
        assert all(o["error"] is None for o in outs)
        assert all(o["num_generated_tokens"] == 5 for o in outs)
        stats = dec.engine_stats()
        assert stats["active_slots"] == 0
        assert stats["free_pages"] == 31
    finally:
        dec.shutdown()


@pytest.fixture
def disagg_app(ray_start_module):
    from ray_tpu import serve
    from ray_tpu.serve.llm.disagg import build_disagg_openai_app

    app = build_disagg_openai_app(_tiny_cfg(), route_prefix="/v1",
                                  num_prefill=2, num_decode=1)
    serve.run(app, name="llm-disagg", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    yield f"http://127.0.0.1:{proxy.port}"
    serve.shutdown()


def test_disagg_openai_http_e2e(disagg_app):
    """End-to-end: distinct prefill replicas and a decode ingress serving
    OpenAI requests over HTTP (VERDICT r2 item 4's done-bar)."""
    def post(payload):
        req = urllib.request.Request(
            f"{disagg_app}/v1/completions",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    outs = [post({"prompt": f"hello {i}", "max_tokens": 4,
                  "temperature": 0.0}) for i in range(4)]
    for out in outs:
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] == 4
        assert out["ray_tpu"]["ttft_s"] is not None

    # chat route must NOT fall through to the plain completions path
    req = urllib.request.Request(
        f"{disagg_app}/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 3}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        chat = json.loads(r.read())
    assert chat["choices"][0]["message"]["role"] == "assistant"

    with urllib.request.urlopen(f"{disagg_app}/v1/models", timeout=30) as r:
        models = json.loads(r.read())
    assert models["data"][0]["mode"] == "disagg"


@pytest.fixture
def disagg_dag_app(ray_start_module):
    from ray_tpu import serve
    from ray_tpu.serve.llm.disagg import build_disagg_openai_app

    app = build_disagg_openai_app(_tiny_cfg(), route_prefix="/v1",
                                  num_prefill=2, num_decode=1,
                                  use_pipeline=True)
    serve.run(app, name="llm-disagg-dag", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    yield f"http://{'127.0.0.1'}:{proxy.port}"
    serve.shutdown()


def test_disagg_dag_pipeline_e2e(disagg_dag_app):
    """The prefill→decode handoff re-expressed on the compiled pipeline
    (mutable-channel aDAG path, VERDICT r3 item 4): same OpenAI surface,
    KV blobs ride channel edges instead of object-plane task returns."""
    def post(payload):
        req = urllib.request.Request(
            f"{disagg_dag_app}/v1/completions",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    outs = [post({"prompt": f"hello {i}", "max_tokens": 4,
                  "temperature": 0.0}) for i in range(4)]
    for out in outs:
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] == 4


def test_handoff_channel_capacity_sizing():
    """ADVICE r4: the compiled-pipeline channel must fit the LARGEST KV
    handoff blob the config can produce (>1 page, model dtype), not the
    8 MiB default that only fit the tiny test config."""
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.config import LLMConfig
    from ray_tpu.serve.llm.disagg import _handoff_channel_capacity

    mc = llama.llama3_1b(max_seq_len=2048)
    cfg = LLMConfig(model_id="x", model_config=mc, page_size=128,
                    max_prompt_len=1024, max_seq_len=2048)
    cap = _handoff_channel_capacity(cfg)
    pages = -(-cfg.max_prompt_len // cfg.page_size)
    assert pages == 8  # a real multi-page prompt
    kv_bytes = 2 * mc.n_layers * mc.n_kv_heads * pages * cfg.page_size \
        * mc.head_dim * np.dtype(mc.dtype).itemsize
    assert cap > kv_bytes          # blob + framing headroom fits
    assert cap > 8 * 1024 * 1024   # and exceeds the old default
    # picklable envelope of that worst-case blob actually fits
    import pickle
    blob = {"kv_k": np.zeros((mc.n_layers, mc.n_kv_heads, pages,
                              cfg.page_size, mc.head_dim),
                             np.dtype(mc.dtype)),
            "kv_v": np.zeros((mc.n_layers, mc.n_kv_heads, pages,
                              cfg.page_size, mc.head_dim),
                             np.dtype(mc.dtype)),
            "prompt_tokens": list(range(cfg.max_prompt_len))}
    assert len(pickle.dumps(blob, protocol=5)) <= cap


def test_handoff_capacity_encoded_sizing():
    """ISSUE 16 satellite: with a wire codec on, the channel is sized
    from the MEASURED raw/encoded ratio — trusting only half of it and
    never dropping below raw sizing (an unmeasured or degenerate probe
    must stay raw-safe; overflow poisons the pipe, headroom is cheap)."""
    from ray_tpu.models import llama
    from ray_tpu.serve.llm.config import LLMConfig
    from ray_tpu.serve.llm.disagg import _handoff_channel_capacity

    mc = llama.llama3_1b(max_seq_len=2048)

    def cap(**kw):
        cfg = LLMConfig(model_id="x", model_config=mc, page_size=128,
                        max_prompt_len=1024, max_seq_len=2048, **kw)
        return _handoff_channel_capacity(
            cfg, measured_ratio=kw.pop("_ratio", None))

    raw = cap(disagg_wire_codec="none")
    # lossless wire, no probe -> raw-safe (ratio floors at 1.0)
    assert cap(disagg_wire_codec="lossless") == raw
    # measured 6x compression -> capacity shrinks, but only by ratio/2
    pages = -(-1024 // 128)
    kv_bytes = 2 * mc.n_layers * mc.n_kv_heads * pages * 128 \
        * mc.head_dim * np.dtype(mc.dtype).itemsize
    shrunk = _handoff_channel_capacity(
        LLMConfig(model_id="x", model_config=mc, page_size=128,
                  max_prompt_len=1024, max_seq_len=2048),
        measured_ratio=6.0)
    assert shrunk < raw
    assert shrunk >= int((kv_bytes / 3.0) * 1.25)  # half of 6x trusted
    # degenerate probe (ratio < 2: half would EXPAND) floors to raw
    assert _handoff_channel_capacity(
        LLMConfig(model_id="x", model_config=mc, page_size=128,
                  max_prompt_len=1024, max_seq_len=2048),
        measured_ratio=0.8) == raw


# ---------------------------------------------------------------------------
# fleet disaggregation on the streamed KV plane (ISSUE 16)
# ---------------------------------------------------------------------------

def _fleet_cfg(**kw):
    """Tier-enabled config shared by the prefill and decode sides — the
    shared kv_tier_namespace over it is what makes prefill registrations
    restorable on decode engines."""
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=96, max_seq_len=160, max_tokens=8,
             prefix_cache_enabled=True, kv_tier_enabled=True)
    d.update(kw)
    return LLMConfig(**d)


def _want_tokens(prompt, cfg=None, max_tokens=8):
    """Greedy ground truth from a cache-off, tier-off engine (same seed
    = same random-init weights as every fleet engine)."""
    from ray_tpu.serve.llm import LLMEngine

    base = cfg or _fleet_cfg()
    import dataclasses
    off = LLMEngine(dataclasses.replace(base, kv_tier_enabled=False,
                                        prefix_cache_enabled=False),
                    rng_seed=0)
    off.start()
    try:
        return off.generate(prompt, max_tokens=max_tokens,
                            temperature=0.0)["tokens"]
    finally:
        off.shutdown()


def test_wire_codec_roundtrip_lossless_and_none():
    """The disagg wire blob must decode bit-exactly under `lossless` and
    pass through untouched under `none` (mixed-codec rollouts: the decode
    side accepts both shapes)."""
    from ray_tpu.serve.llm.disagg import _decode_state, _encode_state

    rng = np.random.default_rng(0)
    kv_k = rng.standard_normal((2, 2, 3, 16, 8)).astype(np.float32)
    kv_v = rng.standard_normal((2, 2, 3, 16, 8)).astype(np.float32)
    state = {"prompt_tokens": [1] * 40, "plen": 40, "n_pages": 3,
             "first_token": 7, "kv_k": kv_k, "kv_v": kv_v,
             "temperature": 0.0, "prefill_ttft_s": 0.01}

    enc = _encode_state(dict(state), "lossless")
    assert "kv_k" not in enc and len(enc["enc_pages"]) == 3
    assert enc["wire_bytes"] > 0
    assert enc["first_token"] == 7  # metadata rides along
    dec = _decode_state(enc)
    np.testing.assert_array_equal(dec["kv_k"], kv_k)
    np.testing.assert_array_equal(dec["kv_v"], kv_v)

    # `none` passes through; raw blobs pass decode untouched
    assert _encode_state(state, "none") is state
    assert _decode_state(state) is state

    # int8: bounded per-(layer,head) quantization error, 4x smaller wire
    enc8 = _encode_state(dict(state), "int8")
    dec8 = _decode_state(enc8)
    bound = max(np.abs(kv_k).max(), np.abs(kv_v).max()) / 127.0 * 1.01
    assert np.abs(dec8["kv_k"] - kv_k).max() <= bound
    assert np.abs(dec8["kv_v"] - kv_v).max() <= bound
    assert enc8["wire_bytes"] < enc["wire_bytes"]


def test_int8_divergence_policy_gate():
    """The quality policy gating int8 on the disagg wire: measured
    greedy divergence against the deployment bound; the default bound
    demands bit-identity so int8 never silently defaults on."""
    from ray_tpu.serve.llm.disagg import (int8_wire_allowed,
                                          int8_wire_divergence)

    assert int8_wire_divergence([1, 2, 3], [1, 2, 3]) == 0.0
    assert int8_wire_divergence([1, 2, 3, 4], [1, 2, 9, 4]) == 0.25
    # length mismatch counts every unmatched position
    assert int8_wire_divergence([1, 2], [1, 2, 5, 6]) == 0.5
    assert int8_wire_divergence([], []) == 0.0

    cfg = _tiny_cfg()
    assert cfg.disagg_int8_max_divergence == 0.0
    assert int8_wire_allowed(cfg, 0.0)
    assert not int8_wire_allowed(cfg, 1e-6)
    loose = _tiny_cfg(disagg_int8_max_divergence=0.05)
    assert int8_wire_allowed(loose, 0.04)
    assert not int8_wire_allowed(loose, 0.06)


def test_prompt_tokens_for_http():
    """Proxy-side prompt sizing for the disagg threshold: mirrors the
    engine's tokenization + max_prompt_len cap; non-LLM routes and
    failures answer 0 (which never crosses a positive threshold)."""
    from ray_tpu.serve import affinity

    from ray_tpu.serve.llm.tokenizer import get_tokenizer

    meta = {"tokenizer": "byte", "page_size": 16, "max_prompt_len": 32}
    assert affinity.prompt_tokens_for_http(
        "/completions", {"prompt": "hello"}, meta) == len(
            get_tokenizer("byte").encode("hello"))
    # capped at the deployment's max_prompt_len, like the engine
    assert affinity.prompt_tokens_for_http(
        "/completions", {"prompt": "x" * 80}, meta) == 32
    chat = {"messages": [{"role": "user", "content": "hi"}]}
    assert affinity.prompt_tokens_for_http(
        "/chat/completions", chat, meta) > 0
    assert affinity.prompt_tokens_for_http("/models", {}, meta) == 0
    assert affinity.prompt_tokens_for_http(
        "/completions", {"prompt": "x"}, {}) == 0  # broken meta degrades


class _AID:
    def __init__(self, h):
        self._h = h

    def hex(self):
        return self._h


class _Rep:
    def __init__(self, name):
        self._actor_id = _AID(name)


def test_router_disagg_plan_threshold_routing():
    """Router.disagg_plan unit contract: the third placement mode fires
    only for deployments advertising a prefill pool, only past the
    threshold, and discounts what the decode pool already holds."""
    import threading

    from ray_tpu.serve.config import RouterConfig
    from ray_tpu.serve.router import ReplicaSet, Router

    rs = ReplicaSet(RouterConfig(), "llm")
    rs.update([_Rep("r0"), _Rep("r1")], 0)
    digs = [f"{i:02x}" * 16 for i in range(6)]
    meta = {"tokenizer": "byte", "page_size": 16, "max_prompt_len": 96,
            "disagg_prefill": "llm-prefill", "disagg_prompt_threshold": 32}
    rs.apply_summaries(1, meta, {"r0": digs[:4]})
    rs.summaries_ok_at = __import__("time").monotonic()

    rtr = Router.__new__(Router)  # disagg_plan touches only _lock/_sets
    rtr._lock = threading.Lock()
    rtr._sets = {"llm": rs}

    # under threshold -> colocated
    assert rtr.disagg_plan("llm", None, 20) is None
    assert rtr.disagg_plan("llm", None, 32) is None  # exactly at: colocated
    # long cold prompt -> prefill pool, full estimate
    plan = rtr.disagg_plan("llm", ["ff" * 16], 90)
    assert plan == {"prefill_deployment": "llm-prefill",
                    "est_prefill_tokens": 90}
    # hot prefix discounts below threshold -> colocated (the handoff only
    # pays for COLD prefill FLOPs)
    assert rtr.disagg_plan("llm", digs[:5], 90) is None  # 90 - 4*16 = 26
    # unknown deployment / no meta / zero prompt -> colocated
    assert rtr.disagg_plan("nope", None, 500) is None
    assert rtr.disagg_plan("llm", None, 0) is None
    plain = ReplicaSet(RouterConfig(), "plain")
    plain.update([_Rep("p0")], 0)
    rtr._sets["plain"] = plain
    assert rtr.disagg_plan("plain", None, 500) is None
    # threshold 0 disables the mode entirely
    rs.apply_summaries(2, dict(meta, disagg_prompt_threshold=0),
                       {"r0": digs[:4]})
    assert rtr.disagg_plan("llm", None, 500) is None
    # stale summaries: no discount evidence -> assume cold, still plan
    rs.apply_summaries(3, meta, {"r0": digs[:4]})
    rs.summaries_ok_at = 0.0
    plan = rtr.disagg_plan("llm", digs[:5], 90)
    assert plan is not None and plan["est_prefill_tokens"] == 90


def test_tier_flush_index_barrier():
    """flush_index drains the ordered publisher queue: once it returns
    True every earlier put is registered (the handshake that lets the
    proxy dispatch the decode leg right after prefill_stream returns)."""
    from ray_tpu.serve.llm.kv_tier import KVTierStore

    store = KVTierStore(max_bytes=1 << 20, disk_dir=None, disk_max_bytes=0,
                        ttl_s=60.0, page_size=16)
    try:
        assert store.flush_index(2.0) is True  # empty queue: immediate
        k = np.zeros((1, 1, 2, 16, 4), np.float32)
        assert store.put(k, k, digests=["aa" * 16, "bb" * 16],
                         tokens=[16, 32]) == 2
        assert store.flush_index(2.0) is True  # drains behind the puts
    finally:
        store.close()


# ---- cluster: streamed handoff over the CP index (keep LAST: the
# module-scoped runtime stays up once started) ------------------------------

FLEET_PROMPT = "the quick brown fox jumps over the lazy dog " * 2  # 88 toks


def test_streamed_handoff_token_identity(ray_start_module):
    """Tentpole contract: a prompt prefilled via prefill_stream (KV
    spilled through the tier codec + CP index) and decoded by a plain
    tier-enabled engine emits the SAME greedy tokens as one engine doing
    both — and the decode engine's restore accounting lands in the
    disagg counters."""
    from ray_tpu.serve.llm.disagg import PrefillServer
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _fleet_cfg()
    prompt = FLEET_PROMPT + "alpha"
    want = _want_tokens(prompt)

    from ray_tpu.serve.llm.tokenizer import get_tokenizer
    ntoks = len(get_tokenizer(cfg.tokenizer).encode(prompt))
    pre = PrefillServer(cfg)
    desc = pre.prefill_stream("/completions", {"prompt": prompt})
    assert desc["plen"] == ntoks
    assert desc["pages_registered"] == ntoks // cfg.page_size
    assert desc["wire_bytes"] > 0
    assert desc["prefill_ttft_s"] > 0

    dec = LLMEngine(cfg, rng_seed=0)
    dec.start()
    try:
        out = dec.generate(prompt, temperature=0.0, disagg=True)
        assert out["error"] is None
        assert out["tokens"] == want
        st = dec.engine_stats()
        assert st["disagg_prefills"] == 1
        assert st["handoff_bytes_wire"] > 0
        assert st["restored_pages"] >= 1
        # prefill-side wire accounting mirrors the handoff
        assert pre.engine_stats()["handoff_bytes_wire"] >= desc["wire_bytes"]
        assert pre.engine_stats()["mode"] == "prefill"
    finally:
        dec.shutdown()


def test_dead_prefill_degrades_to_partial_restore(ray_start_module):
    """Satellite: a prefill replica dying mid-stream (chunk fault seam)
    degrades the decode side to a PARTIAL restore + tail prefill — the
    request still completes greedy-identical, restore_partial is
    counted, and the partial flag rides the restore stage attrs (what
    the proxy's breaker charge keys on)."""
    from ray_tpu.serve.llm.disagg import PrefillServer
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _fleet_cfg(kv_tier_chunk_pages=2)
    prompt = FLEET_PROMPT + "bravo"
    want = _want_tokens(prompt, cfg=cfg)

    from ray_tpu.serve.llm.tokenizer import get_tokenizer
    ntoks = len(get_tokenizer(cfg.tokenizer).encode(prompt))
    pre = PrefillServer(cfg)
    desc = pre.prefill_stream("/completions", {"prompt": prompt})
    assert desc["pages_registered"] == ntoks // cfg.page_size

    dec = LLMEngine(cfg, rng_seed=0)
    dec.start()

    def fault(chunk_idx):
        if chunk_idx >= 1:  # first chunk lands, then the owner "dies"
            raise RuntimeError("prefill replica died mid-stream")

    dec._kv_tier._chunk_fault = fault
    try:
        out = dec.generate(prompt, temperature=0.0, disagg=True)
        assert out["error"] is None
        assert out["tokens"] == want  # tail prefill recomputed the rest
        st = dec.engine_stats()
        assert st["restore_partial"] >= 1
        assert st["disagg_prefills"] == 1
        assert 1 <= st["restored_pages"] < desc["pages_registered"]
        restore = [s for s in out["stages"] if s["stage"] == "restore"]
        assert restore and restore[-1]["attrs"]["partial"] is True
    finally:
        dec.shutdown()


@pytest.fixture
def fleet_app(ray_start_module):
    from ray_tpu import serve
    from ray_tpu.serve.llm.disagg import build_disagg_fleet_app

    cfg = _fleet_cfg(disagg_prompt_threshold=32)
    app = build_disagg_fleet_app(cfg, route_prefix="/v1",
                                 num_prefill=1, num_decode=1)
    serve.run(app, name="llm-fleet", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    yield f"http://127.0.0.1:{proxy.port}", cfg
    serve.shutdown()


@pytest.mark.slow
def test_fleet_disagg_http_e2e(fleet_app):
    """End-to-end fleet disagg: long prompts route through the prefill
    pool (router plan -> prefill_stream -> streamed restore on the
    decode ingress), the proxy/engine disagg counters move, roles show
    in controller status, and the served completion is greedy-identical
    to a monolithic engine."""
    import time as _time

    base, cfg = fleet_app

    def post(payload):
        req = urllib.request.Request(
            f"{base}/v1/completions", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def proxy_stats():
        with urllib.request.urlopen(f"{base}/-/stats", timeout=30) as r:
            return json.loads(r.read())

    # each attempt uses a FRESH long prompt: a served prompt's prefix
    # goes resident on the decode replica, and the plan's discount then
    # (correctly) keeps repeats colocated — only cold prompts disagg
    deadline = _time.monotonic() + 180
    hit_prompt, hit_out, i = None, None, 0
    while _time.monotonic() < deadline and hit_prompt is None:
        prompt = f"req{i:03d} " + FLEET_PROMPT
        out = post({"prompt": prompt, "max_tokens": 6, "temperature": 0.0})
        assert out["usage"]["completion_tokens"] == 6
        if proxy_stats()["disagg_prefills"] >= 1:
            hit_prompt, hit_out = prompt, out
        i += 1
        _time.sleep(0.5)
    assert hit_prompt is not None, \
        "no request took the disagg path within the deadline"

    # greedy identity across the whole disagg path
    want = _want_tokens(hit_prompt, cfg=cfg, max_tokens=6)
    from ray_tpu.serve.llm.tokenizer import get_tokenizer
    assert hit_out["choices"][0]["text"] == get_tokenizer(
        cfg.tokenizer).decode(want)

    # roles + engine counters through the controller
    import ray_tpu as _rt
    from ray_tpu.serve.controller import get_or_create_controller
    rows = _rt.get(get_or_create_controller().detailed_status.remote(),
                   timeout=30.0)
    fleet = {k: v for k, v in rows.items() if v.get("app") == "llm-fleet"}
    assert {"prefill", "decode"} <= {v.get("role") for v in fleet.values()}
    decode_engines = [e for v in fleet.values()
                      if v.get("role") == "decode"
                      for e in (v.get("engine") or []) if e]
    assert decode_engines
    assert any(e.get("disagg_prefills", 0) >= 1 for e in decode_engines)
    assert any(e.get("handoff_bytes_wire", 0) > 0 for e in decode_engines)
    assert all(e.get("handoff_overlap_ms", 0.0) >= 0.0
               for e in decode_engines)
