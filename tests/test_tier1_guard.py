"""Tier-1 drift guard: chaos and heavyweight multi-node tests must carry
`@pytest.mark.slow` so the tier-1 gate (`pytest -m 'not slow'`) stays fast
and deterministic.

The guard now rides the graftlint pass framework (`tier1-marks` in
ray_tpu/analysis/passes_tests.py) instead of a hand-rolled AST walk; the
semantics are unchanged — static scan, no imports, no collection side
effects. The allowlist freezes the seed-era exceptions — do NOT grow it
for new tests; mark them slow instead.
"""

import pathlib

from ray_tpu.analysis.core import ModuleSource
from ray_tpu.analysis.passes_tests import (ADD_NODE_MIN, CHAOS_NAMES,
                                           FROZEN_ALLOWLIST, Tier1MarksPass)


def test_allowlist_is_frozen():
    # the allowlist is the seed-era set, verbatim. Growing it is the
    # drift this guard exists to catch — new chaos/multi-node tests get
    # @pytest.mark.slow instead.
    assert FROZEN_ALLOWLIST == frozenset({
        "test_node_killer_lineage_reconstruction",
        "test_chaos_worker_killer_workload_completes",
        "test_faultschedule_validates_and_fires_rpc_faults",
        "test_worker_killer_max_kills",
    })
    assert CHAOS_NAMES == frozenset(
        {"WorkerKiller", "NodeKiller", "FaultSchedule"})
    assert ADD_NODE_MIN == 3


def test_chaos_and_multinode_tests_are_slow_marked():
    here = pathlib.Path(__file__).parent
    guard = Tier1MarksPass()
    offenders = []
    for path in sorted(here.glob("test_*.py")):
        if path.name == pathlib.Path(__file__).name:
            continue
        module = ModuleSource(str(path), path.name, path.read_text())
        for f in guard.run(module):
            offenders.append(f.format())
    assert not offenders, (
        "chaos/multi-node tests must be @pytest.mark.slow so tier-1 stays "
        "fast (or, exceptionally, added to FROZEN_ALLOWLIST in "
        "ray_tpu/analysis/passes_tests.py):\n  " + "\n  ".join(offenders))
