"""Streaming generator returns (num_returns="streaming") — the analog of the
reference's ObjectRefGenerator protocol (core_worker.proto:513
ReportGeneratorItemReturns; python/ray/tests/test_streaming_generator.py)."""

import time

import pytest

import ray_tpu


def test_streaming_task_basic(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref) for ref in gen.remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_streaming_incremental_delivery(ray_start_regular):
    """Items are consumable BEFORE the generator finishes (the whole point:
    the reference streams Data blocks / Serve tokens through this)."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            yield i
            time.sleep(0.8)

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(g), timeout=30)
    first_latency = time.monotonic() - t0
    assert first == 0
    # the generator still has ~2.4s of sleeps left when item 0 arrives
    assert first_latency < 2.0, f"first item took {first_latency:.1f}s"
    assert [ray_tpu.get(r) for r in g] == [1, 2, 3]


def test_streaming_large_items_via_shm(ray_start_regular):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def gen_blocks():
        for i in range(3):
            yield np.full(100_000, i, np.int64)  # ~800KB, above inline cap

    outs = [ray_tpu.get(r) for r in gen_blocks.remote()]
    assert [int(o[0]) for o in outs] == [0, 1, 2]
    assert all(len(o) == 100_000 for o in outs)


def test_streaming_midstream_error(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("boom mid-stream")

    g = bad_gen.remote()
    assert ray_tpu.get(next(g)) == 1
    assert ray_tpu.get(next(g)) == 2
    with pytest.raises(Exception, match="boom"):
        ray_tpu.get(next(g))
    with pytest.raises(StopIteration):
        next(g)


def test_streaming_actor_method(ray_start_regular):
    @ray_tpu.remote
    class Producer:
        def stream(self, n):
            for i in range(n):
                yield f"tok{i}"

    p = Producer.remote()
    g = p.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in g] == ["tok0", "tok1", "tok2"]


def test_streaming_backpressure_bounds_producer(ray_start_regular):
    """The producer may run at most streaming_backpressure_items ahead of
    the CONSUMER's cursor (not just of delivery): with no consumption, a
    200-item firehose stalls at the window."""
    @ray_tpu.remote(num_returns="streaming")
    def firehose():
        import os
        for i in range(200):
            with open("/tmp/firehose_progress.txt", "w") as f:
                f.write(str(i))
            yield i

    import os
    try:
        os.unlink("/tmp/firehose_progress.txt")
    except OSError:
        pass
    g = firehose.remote()
    time.sleep(3.0)  # no consumption: the producer must stall at the window
    with open("/tmp/firehose_progress.txt") as f:
        produced = int(f.read())
    assert produced < 60, f"producer ran {produced} items ahead of consumer"
    out = [ray_tpu.get(r) for r in g]
    assert out == list(range(200))


def test_streaming_retry_exceptions_reruns_generator(ray_start_regular):
    """retry_exceptions matches non-streaming semantics: the whole
    generator re-runs instead of surfacing a transient error mid-stream."""
    import os

    marker = "/tmp/stream_retry_marker.txt"
    try:
        os.unlink(marker)
    except OSError:
        pass

    @ray_tpu.remote(num_returns="streaming", retry_exceptions=True,
                    max_retries=2)
    def flaky():
        yield 1
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("x")
            raise RuntimeError("transient")
        yield 2

    assert [ray_tpu.get(r) for r in flaky.remote()] == [1, 2]


def test_streaming_abandoned_generator_cleanup(ray_start_regular):
    """Dropping the generator mid-stream frees buffered items and unblocks
    the producer (no permanent pin at the owner)."""
    from ray_tpu.core import api

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(50):
            yield bytes(1000) + bytes([i])

    g = gen.remote()
    first = ray_tpu.get(next(g), timeout=30)
    assert first[-1] == 0
    tid = g._stream.task_id
    del g  # abandon mid-stream
    rt = api._get_runtime()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if rt.stream_manager.get(tid) is None \
                and rt.task_manager.get_pending_spec(tid) is None:
            break
        time.sleep(0.2)
    assert rt.stream_manager.get(tid) is None


def test_streaming_refs_feed_downstream_tasks(ray_start_regular):
    """Streamed item refs are first-class: pass them to other tasks."""
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    @ray_tpu.remote
    def double(x):
        return 2 * x

    outs = ray_tpu.get([double.remote(r) for r in gen.remote(4)], timeout=60)
    assert outs == [0, 2, 4, 6]
