"""`python -m ray_tpu <cmd>` — the ray-tpu CLI entry point
(reference: `ray` console script, python/ray/scripts/scripts.py)."""

from ray_tpu.scripts.cli import main

main()
