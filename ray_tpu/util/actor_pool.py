"""ActorPool (reference: /root/reference/python/ray/util/actor_pool.py):
round-robin work distribution over a fixed set of actors with
ordered/unordered result retrieval."""

from __future__ import annotations

from typing import Any, Callable, Iterator

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._inflight_owner: dict = {}
        self._result_futures: dict = {}
        self._submit_seq = 0
        self._drain_seq = 0
        self._backlog: list = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef; queues if all actors busy."""
        if self._idle:
            actor = self._idle.pop(0)
            future = fn(actor, value)
            self._inflight_owner[future] = actor
            self._result_futures[self._submit_seq] = future
            self._submit_seq += 1
        else:
            self._backlog.append((fn, value))

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._backlog:
            self.submit(*self._backlog.pop(0))

    def has_next(self) -> bool:
        return bool(self._result_futures)

    def get_next(self, timeout: float | None = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        # Wait with the timeout BEFORE mutating pool state so a TimeoutError
        # leaves the pool intact (reference actor_pool.py does ray.wait first).
        future = self._result_futures[self._drain_seq]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        del self._result_futures[self._drain_seq]
        self._drain_seq += 1
        try:
            result = ray_tpu.get(future)
        finally:
            self._return_actor(self._inflight_owner.pop(future))
        return result

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._inflight_owner),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        for idx, f in list(self._result_futures.items()):
            if f == future:
                del self._result_futures[idx]
                break
        result = ray_tpu.get(future)
        self._return_actor(self._inflight_owner.pop(future))
        return result

    def map(self, fn: Callable, values: list) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: list) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop(0) if self._idle else None

    def push(self, actor):
        self._return_actor(actor)
