"""Search spaces + search algorithms.

TPU-native analog of the reference's tune search layer
(/root/reference/python/ray/tune/search/ — sample.py domains,
basic_variant.py BasicVariantGenerator grid/random, plus the Searcher ABC
that optuna/hyperopt/etc. plug into). In-tree: grid + random (the
reference's default path) and a simple TPE-less `Searcher` hook point.
"""

from __future__ import annotations

import dataclasses
import itertools
import random as _random
from typing import Any, Callable, Optional


# ---- sampling domains ----------------------------------------------------


@dataclasses.dataclass
class Domain:
    def sample(self, rng: _random.Random) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class GridSearch:
    values: list

    # grid is not sampled; expanded by the variant generator


@dataclasses.dataclass
class Choice(Domain):
    values: list

    def sample(self, rng):
        return rng.choice(self.values)


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclasses.dataclass
class SampleFrom(Domain):
    fn: Callable

    def sample(self, rng):
        return self.fn(None)


def grid_search(values: list) -> GridSearch:
    return GridSearch(list(values))


def choice(values: list) -> Choice:
    return Choice(list(values))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def sample_from(fn: Callable) -> SampleFrom:
    return SampleFrom(fn)


# ---- variant generation --------------------------------------------------


class BasicVariantGenerator:
    """Grid axes are fully expanded; Domain axes are sampled num_samples
    times (reference basic_variant.py semantics: num_samples multiplies the
    grid)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = param_space
        self._num_samples = num_samples
        self._rng = _random.Random(seed)

    def variants(self) -> list[dict]:
        grid_keys = {}
        flat = _flatten(self._space)
        for key, value in flat.items():
            if isinstance(value, GridSearch):
                grid_keys[key] = value.values
        grids = [dict(zip(grid_keys, combo))
                 for combo in itertools.product(*grid_keys.values())] or [{}]
        out = []
        for _ in range(self._num_samples):
            for grid in grids:
                cfg = {}
                for key, value in flat.items():
                    if key in grid:
                        cfg[key] = grid[key]
                    elif isinstance(value, Domain):
                        cfg[key] = value.sample(self._rng)
                    else:
                        cfg[key] = value
                out.append(_unflatten(cfg))
        return out


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


def _unflatten(d: dict) -> dict:
    out: dict = {}
    for k, v in d.items():
        parts = k.split("/")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out


# ---- searchers (reference: tune/search/searcher.py ABC + optuna/hyperopt
# plugins; here a native TPE so no external dependency is needed) ----------


class Searcher:
    """Suggest/observe protocol (reference Searcher ABC)."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False) -> None:
        pass


class TPESearcher(Searcher):
    """Native tree-structured Parzen estimator (the algorithm behind
    hyperopt — reference integrates it via tune/search/hyperopt). Models
    each dimension independently: observed results split into good (top
    ``gamma`` quantile) and bad; candidates are drawn from the good
    distribution and ranked by the good/bad density ratio.
    """

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        assert mode in ("max", "min")
        self._flat_space = _flatten(space)
        for k, v in self._flat_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    f"{k}: grid_search is not a samplable domain; use "
                    f"choice() with TPESearcher")
        self._metric = metric
        self._mode = mode
        self._n_initial = n_initial
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._rng = _random.Random(seed)
        self._observed: list[tuple[dict, float]] = []  # (flat_cfg, score)
        self._pending: dict[str, dict] = {}

    # -- scoring helpers --
    def _score(self, result: dict) -> Optional[float]:
        v = result.get(self._metric) if result else None
        if v is None:
            return None
        return float(v) if self._mode == "max" else -float(v)

    def _split(self) -> tuple[list[dict], list[dict]]:
        ranked = sorted(self._observed, key=lambda t: -t[1])
        n_good = max(1, int(len(ranked) * self._gamma))
        return ([c for c, _ in ranked[:n_good]],
                [c for c, _ in ranked[n_good:]] or [c for c, _ in ranked])

    def _density(self, value, key, domain, configs) -> float:
        vals = [c[key] for c in configs if key in c]
        if not vals:
            return 1e-12
        if isinstance(domain, Choice):
            counts = sum(1 for v in vals if v == value)
            return (counts + 1.0) / (len(vals) + len(domain.values))
        import math
        lo, hi = _domain_range(domain)
        log = isinstance(domain, LogUniform)
        x = math.log(value) if log else float(value)
        pts = [math.log(v) if log else float(v) for v in vals]
        bw = max((hi - lo) / max(len(pts) ** 0.5, 1.0), 1e-9)
        return sum(math.exp(-0.5 * ((x - p) / bw) ** 2) for p in pts) \
            / (len(pts) * bw) + 1e-12

    def _sample_dim(self, key, domain, good, bad):
        if not isinstance(domain, Domain):
            return domain  # constant
        best_v, best_ratio = None, -1.0
        for _ in range(self._n_candidates):
            # candidate from the good distribution (perturb a good point)
            if good and self._rng.random() < 0.8:
                base = self._rng.choice(good).get(key)
                v = self._perturb(domain, base) if base is not None \
                    else domain.sample(self._rng)
            else:
                v = domain.sample(self._rng)
            ratio = (self._density(v, key, domain, good)
                     / self._density(v, key, domain, bad))
            if ratio > best_ratio:
                best_v, best_ratio = v, ratio
        return best_v

    def _perturb(self, domain, base):
        import math
        if isinstance(domain, Choice):
            return base if self._rng.random() < 0.7 \
                else domain.sample(self._rng)
        lo, hi = _domain_range(domain)
        if isinstance(domain, LogUniform):
            x = math.log(base) + self._rng.gauss(0, (hi - lo) * 0.2)
            return math.exp(min(max(x, lo), hi))
        v = base + self._rng.gauss(0, (hi - lo) * 0.2)
        v = min(max(v, lo), hi)
        return int(round(v)) if isinstance(domain, RandInt) else v

    # -- Searcher API --
    def suggest(self, trial_id: str) -> dict:
        if len(self._observed) < self._n_initial:
            flat = {k: (v.sample(self._rng) if isinstance(v, Domain) else v)
                    for k, v in self._flat_space.items()}
        else:
            good, bad = self._split()
            flat = {k: self._sample_dim(k, v, good, bad)
                    for k, v in self._flat_space.items()}
        self._pending[trial_id] = flat
        return _unflatten(flat)

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False) -> None:
        flat = self._pending.pop(trial_id, None)
        score = None if error else self._score(result)
        if flat is not None and score is not None:
            self._observed.append((flat, score))


def _domain_range(domain) -> tuple[float, float]:
    import math
    if isinstance(domain, LogUniform):
        return math.log(domain.low), math.log(domain.high)
    if isinstance(domain, (Uniform, RandInt)):
        return float(domain.low), float(domain.high)
    return 0.0, 1.0


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference search/concurrency_limiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self._searcher = searcher
        self._max = max_concurrent
        self._live: set[str] = set()

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._live) >= self._max:
            return None
        cfg = self._searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False) -> None:
        self._live.discard(trial_id)
        self._searcher.on_trial_complete(trial_id, result, error)


class RandomSearcher(Searcher):
    """Independent random sampling under the Searcher protocol (the
    baseline TPE must beat; reference basic_variant random sampling)."""

    def __init__(self, space: dict, seed: Optional[int] = None):
        self._flat_space = _flatten(space)
        for k, v in self._flat_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    f"{k}: grid_search is not a samplable domain; use "
                    f"choice() with RandomSearcher")
        self._rng = _random.Random(seed)

    def suggest(self, trial_id: str) -> dict:
        return _unflatten({
            k: (v.sample(self._rng) if isinstance(v, Domain) else v)
            for k, v in self._flat_space.items()})


class OptunaSearch(Searcher):
    """External-searcher adapter backed by optuna (reference:
    tune/search/optuna/optuna_search.py). The tune Domain space is mapped
    onto an optuna study's ask/tell interface; any sampler optuna offers
    (TPE, CMA-ES, ...) drives suggestions.

    optuna is an OPTIONAL dependency: constructing this searcher without
    it raises ImportError with the install hint (this image ships without
    optuna — the in-tree TPESearcher covers the same role natively).
    """

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 sampler=None, seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package (pip install "
                "optuna); the in-tree TPESearcher needs no extra "
                "dependency") from e
        assert mode in ("max", "min")
        self._optuna = optuna
        self._flat_space = _flatten(space)
        self._metric = metric
        self._mode = mode
        if sampler is None:
            sampler = optuna.samplers.TPESampler(seed=seed)
        optuna.logging.set_verbosity(optuna.logging.WARNING)
        self._study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=sampler)
        self._trials: dict[str, object] = {}

    def _suggest_dim(self, trial, key: str, domain):
        if isinstance(domain, Choice):
            return trial.suggest_categorical(key, list(domain.values))
        if isinstance(domain, LogUniform):
            return trial.suggest_float(key, domain.low, domain.high, log=True)
        if isinstance(domain, Uniform):
            return trial.suggest_float(key, domain.low, domain.high)
        if isinstance(domain, RandInt):
            return trial.suggest_int(key, domain.low, domain.high - 1)
        if isinstance(domain, SampleFrom):
            raise ValueError(f"{key}: sample_from is not translatable to "
                             "optuna distributions")
        if isinstance(domain, GridSearch):
            raise ValueError(f"{key}: use choice() instead of grid_search "
                             "with OptunaSearch")
        return domain  # constant

    def suggest(self, trial_id: str) -> dict:
        t = self._study.ask()
        self._trials[trial_id] = t
        return _unflatten({k: self._suggest_dim(t, k, v)
                           for k, v in self._flat_space.items()})

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False) -> None:
        t = self._trials.pop(trial_id, None)
        if t is None:
            return
        value = (result or {}).get(self._metric)
        if error or value is None:
            self._study.tell(t, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(t, float(value))


class BOHBSearcher(TPESearcher):
    """BOHB's model half (reference: TuneBOHB pairs with HyperBandForBOHB;
    here the TPE model is in-tree): keeps observations PER RUNG and fits
    the split on the deepest rung with >= n_min results, so cheap
    low-fidelity evaluations guide early sampling and high-fidelity ones
    take over as they accumulate (the BOHB fidelity schedule)."""

    def __init__(self, space: dict, *, metric: str, mode: str = "max",
                 n_min: int = 6, **kw):
        # the TPE model gate must match the rung rule, or a qualifying
        # rung with n_min..n_initial-1 points would leave suggestions
        # uniform-random despite usable data
        kw.setdefault("n_initial", n_min)
        super().__init__(space, metric=metric, mode=mode, **kw)
        self._n_min = n_min
        self._rungs: dict[float, list[tuple[dict, float]]] = {}

    def observe_rung(self, config: dict, value: float, rung: float) -> None:
        score = float(value) if self._mode == "max" else -float(value)
        flat = {k: v for k, v in _flatten(config).items()
                if k in self._flat_space}
        self._rungs.setdefault(rung, []).append((flat, score))
        # the TPE split reads self._observed: point it at the deepest
        # rung that has enough data (BOHB's model-selection rule)
        deep = [r for r in sorted(self._rungs, reverse=True)
                if len(self._rungs[r]) >= self._n_min]
        if deep:
            self._observed = list(self._rungs[deep[0]])
        else:
            # no rung qualifies yet: fall back to the data-richest rung
            # (low fidelity beats no model — the BOHB fallback)
            richest = max(self._rungs, key=lambda r: len(self._rungs[r]))
            self._observed = list(self._rungs[richest])


def create_bohb(space: dict, *, metric: str, mode: str = "max",
                max_t: int = 100, grace_period: int = 1,
                reduction_factor: float = 3.0, seed=None):
    """Wire the BOHB pair: returns (searcher, scheduler) to pass as
    TuneConfig(search_alg=..., scheduler=...)."""
    from ray_tpu.tune.schedulers import HyperBandForBOHB

    searcher = BOHBSearcher(space, metric=metric, mode=mode, seed=seed)
    scheduler = HyperBandForBOHB(
        searcher=searcher, metric=metric, mode=mode, max_t=max_t,
        grace_period=grace_period, reduction_factor=reduction_factor)
    return searcher, scheduler
