"""State API: cluster introspection.

TPU-native analog of the reference's state API
(/root/reference/python/ray/util/state/api.py — list_actors:783,
list_tasks:1010, list_objects:1055; backed by
dashboard/state_aggregator.py + GCS task events gcs_task_manager.cc). Here
the control plane is the single source of truth, so the listing calls go
straight to it; `timeline()` renders task events as a chrome trace like
ray.timeline (python/ray/_private/state.py:438).
"""

from __future__ import annotations

import json
from typing import Any, Optional


def _cp():
    from ray_tpu.core import api
    return api._get_runtime().cp_client


def list_nodes() -> list[dict]:
    import ray_tpu
    return ray_tpu.nodes()


def drain_node(node_id, wait: bool = False, reason: str = "") -> dict:
    """Gracefully drain one node (the DrainRaylet analog): the node stops
    taking new leases, in-flight work runs to completion (bounded by
    `drain_deadline_s`), primary objects migrate to a survivor, then the
    node deregisters as DRAINED. `wait=True` blocks until the drain
    finishes. `node_id` may be a NodeID or its hex string (prefix ok)."""
    cp = _cp()
    if isinstance(node_id, str):
        matches = [n["node_id"] for n in cp.call("get_nodes", None)
                   if n["node_id"].hex().startswith(node_id)]
        if not matches:
            raise ValueError(f"no node matching {node_id!r}")
        if len(matches) > 1:
            raise ValueError(f"ambiguous node id prefix {node_id!r}")
        node_id = matches[0]
    from ray_tpu.core.config import get_config
    body: dict[str, Any] = {"node_id": node_id, "wait": wait}
    if reason:
        body["reason"] = reason
    timeout = (get_config().drain_deadline_s + 60.0) if wait else 10.0
    return cp.call("drain_node", body, timeout=timeout)


def list_actors(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    out = _cp().call("list_actors", {"limit": limit})
    for a in out:
        for key in ("actor_id", "node_id"):
            if hasattr(a.get(key), "hex"):
                a[key] = a[key].hex()
    return _apply_filters(out[:limit], filters)


def list_placement_groups(limit: int = 1000) -> list[dict]:
    pgs = _cp().call("list_pgs", None)
    for p in pgs:
        p["pg_id"] = p["pg_id"].hex() if hasattr(p["pg_id"], "hex") else p["pg_id"]
    return pgs[:limit]


def list_jobs(limit: int = 1000) -> list[dict]:
    return _cp().call("list_jobs", None)[:limit]


def list_tasks(filters: Optional[list] = None, limit: int = 1000) -> list[dict]:
    events = _cp().call("list_task_events", {"limit": limit * 4})
    # fold events into per-task latest state
    tasks: dict[str, dict] = {}
    for ev in events:
        tid = ev["task_id"]
        rec = tasks.setdefault(tid, {"task_id": tid, "name": ev.get("name", ""),
                                     "state": "", "events": []})
        rec["state"] = ev["state"]
        rec["events"].append({"state": ev["state"], "ts": ev["ts"]})
        if ev.get("name"):
            rec["name"] = ev["name"]
    out = list(tasks.values())[:limit]
    return _apply_filters(out, filters)


def summarize_tasks() -> dict:
    counts: dict[str, int] = {}
    for t in list_tasks(limit=100000):
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return counts


def summarize_actors() -> dict:
    counts: dict[str, int] = {}
    for a in list_actors(limit=100000):
        counts[a["state"]] = counts.get(a["state"], 0) + 1
    return counts


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op == "=" and str(have) != str(value):
                ok = False
            elif op == "!=" and str(have) == str(value):
                ok = False
        if ok:
            out.append(row)
    return out


def timeline(filename: Optional[str] = None) -> Optional[str]:
    """Chrome-trace dump of task events (reference
    _private/state.py:438 chrome_tracing_dump)."""
    events = _cp().call("list_task_events", {"limit": 100000})
    # group begin/end per task attempt
    begun: dict[str, dict] = {}
    trace = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            begun[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in begun:
            b = begun.pop(tid)
            trace.append({
                "cat": "task", "ph": "X",
                "name": ev.get("name") or b.get("name") or tid[:8],
                "pid": ev.get("node_id", "node")[:8],
                "tid": ev.get("worker_id", "worker")[:8],
                "ts": b["ts"] * 1e6,
                "dur": (ev["ts"] - b["ts"]) * 1e6,
                "args": {"task_id": tid, "state": ev["state"]},
            })
    payload = json.dumps(trace)
    if filename:
        with open(filename, "w") as f:
            f.write(payload)
        return None
    return payload


def query_metrics(name: str, tags: Optional[dict] = None,
                  since: Optional[float] = None,
                  until: Optional[float] = None) -> Optional[dict]:
    """Points of one metric from the control-plane time-series store
    (util/metrics.py flusher pipeline): per-source series filtered by a
    tag subset and a [since, until] epoch-seconds range, plus `merged`
    (the cross-source cumulative merge) for histograms. None if the
    metric has never been reported."""
    return _cp().call("metrics_query", {
        "name": name, "tags": tags, "since": since, "until": until})


def list_metric_series(prefix: str = "") -> list[dict]:
    """Catalogue of stored metric series ({name, kind, tags, source,
    points, last_ts}), optionally filtered by name prefix."""
    return _cp().call("metrics_list_series", {"prefix": prefix}) or []


def list_traces(limit: int = 100) -> list[dict]:
    """Summaries of traces in the control-plane trace store, newest first
    (observability/tracing.py; ref: the reference's tracing export)."""
    return _cp().call("list_traces", {"limit": limit}) or []


def get_trace(trace_id: str) -> Optional[dict]:
    """One stitched trace ({trace_id, meta, spans}) by id or id prefix."""
    return _cp().call("get_trace", {"trace_id": trace_id})


def trace_timeline(trace_id: str, filename: Optional[str] = None,
                   fmt: str = "chrome") -> Optional[str]:
    """Export one trace as Chrome-trace JSON (chrome://tracing /
    Perfetto-loadable, same event shape as timeline()) or OTLP-JSON
    (`fmt="otlp"`, collector-importable)."""
    from ray_tpu.observability import tracing

    trace = get_trace(trace_id)
    if trace is None:
        raise ValueError(f"no trace matching {trace_id!r}")
    if fmt == "otlp":
        payload = json.dumps(tracing.to_otlp_json(trace["spans"]))
    else:
        payload = json.dumps(tracing.to_chrome_trace(trace["spans"]))
    if filename:
        with open(filename, "w") as f:
            f.write(payload)
        return None
    return payload


def worker_logs(worker_id: Optional[str] = None,
                tail: int = 200) -> dict[str, str]:
    """Read per-worker stdout/stderr captured by the node agent
    (reference: per-worker files under /tmp/ray/session_*/logs, tailed by
    _private/log_monitor.py). Returns {log_file_name: last `tail` lines}.

    `worker_id` (hex prefix ok) filters to one worker's files.
    """
    import glob
    import os

    from ray_tpu.core.config import get_config

    roots = []
    if get_config().log_dir:
        roots.append(get_config().log_dir)
    roots.extend(glob.glob("/tmp/ray_tpu_logs/agent-*"))
    out: dict[str, str] = {}
    for root in roots:
        for path in sorted(glob.glob(os.path.join(root, "worker-*.out")) +
                           glob.glob(os.path.join(root, "worker-*.err"))):
            name = os.path.basename(path)
            if worker_id and worker_id[:12] not in name:
                continue
            try:
                with open(path, "r", errors="replace") as f:
                    lines = f.readlines()
            except OSError:
                continue
            if lines:
                out[name] = "".join(lines[-tail:])
    return out


def dump_cluster_stacks() -> dict[str, str]:
    """Python stack snapshot of every process in the cluster — the driver,
    each node agent, and each registered worker (ref: the dashboard's
    py-spy profiling endpoints, dashboard/modules/reporter/
    profile_manager.py:191). The tool that turns "the job is stuck" into a
    diagnosis in one call."""
    from ray_tpu.core import api
    from ray_tpu.observability.profiling import dump_thread_stacks

    rt = api._get_runtime()
    out = {"driver": dump_thread_stacks()}
    try:
        nodes = rt.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
    except Exception as e:  # noqa: BLE001
        out["control-plane"] = f"<unreachable: {e!r}>"
        return out
    for n in nodes:
        nid = n["node_id"].hex()[:12] if hasattr(n["node_id"], "hex") \
            else str(n["node_id"])[:12]
        try:
            stacks = rt.peer_pool.get(tuple(n["addr"])).call(
                "dump_node_stacks", None, timeout=30.0, connect_timeout=3.0)
            for name, text in stacks.items():
                out[f"node-{nid}/{name}"] = text
        except Exception as e:  # noqa: BLE001
            out[f"node-{nid}"] = f"<unreachable: {e!r}>"
    return out


def profiling_start(node_id: Optional[str] = None,
                    logdir: Optional[str] = None) -> dict:
    """Begin an XPlane (jax.profiler) capture on the selected node's
    workers — every alive node when `node_id` is None. Routed CP → node
    agent → worker; returns per-node/per-worker start results."""
    body: dict = {}
    if node_id:
        body["node_id"] = node_id
    if logdir:
        body["logdir"] = logdir
    return _cp().call("profiling_start", body, timeout=90.0)


def profiling_stop(node_id: Optional[str] = None) -> dict:
    """End the active captures; the CP registers each produced trace
    directory as a `profile_artifact:<id>` (see list_profile_artifacts)
    and the result carries the registered artifact records."""
    body = {"node_id": node_id} if node_id else {}
    return _cp().call("profiling_stop", body, timeout=90.0)


def capture_xprof(node_id: Optional[str] = None, duration: float = 3.0,
                  logdir: Optional[str] = None) -> dict:
    """One-shot cluster capture: start, run for `duration` seconds, stop.
    Returns the stop result — `result["artifacts"]` lists the XPlane
    trace directories (open them with `tensorboard --logdir <dir>`,
    Profile tab). The `ray-tpu profile` CLI and the dashboard's
    `/api/profile?node=` endpoint both drive this."""
    import time as _time

    start = profiling_start(node_id=node_id, logdir=logdir)
    try:
        _time.sleep(max(0.0, float(duration)))
    finally:
        out = profiling_stop(node_id=node_id)
    out["start"] = start
    return out


def list_profile_artifacts() -> list[dict]:
    """Registered capture artifacts (newest first): id, kind, node,
    worker, pid, logdir, duration."""
    return _cp().call("list_profile_artifacts", None, timeout=10.0) or []


def save_device_memory_profile(node_id: Optional[str] = None,
                               path: Optional[str] = None) -> dict:
    """Dump each selected worker's device (HBM) memory profile (pprof) —
    the remote 'why is replica 3 OOMing' tool."""
    body: dict = {}
    if node_id:
        body["node_id"] = node_id
    if path:
        body["path"] = path
    return _cp().call("save_device_memory_profile", body, timeout=90.0)


def list_kv_tier() -> dict:
    """Cluster-wide tiered-KV-cache prefix index (serve/llm/kv_tier.py):
    one entry per spilled page (owner replica/node, tier, token length,
    bytes) plus the CP-side match/hit counters. The `ray-tpu kvtier` CLI
    and the dashboard's kvtier table render this."""
    return _cp().call("kv_tier_index", {}, timeout=10.0) or {
        "entries": [], "counters": {}}


def slo_report(deployment: Optional[str] = None) -> dict:
    """Fleet tail-latency breakdown over the CP SLO exemplar store
    (observability/attribution.py): per-stage p50/p95/p99, dominant-stage
    attribution for tail requests, per-replica skew. The `ray-tpu slo`
    CLI and the dashboard SLO panel render this."""
    body = {"deployment": deployment} if deployment else {}
    return _cp().call("slo_report", body, timeout=10.0) or {
        "count": 0, "violations": 0, "stage_ms": {},
        "dominant_stage": {}, "replica_skew": {}}


def list_slo_exemplars(limit: int = 50,
                       kind: Optional[str] = None) -> list[dict]:
    """Exemplar summaries, newest first; `kind` filters to "violation"
    or "baseline"."""
    body: dict[str, Any] = {"limit": limit}
    if kind:
        body["kind"] = kind
    return _cp().call("list_slo_exemplars", body, timeout=10.0) or []


def get_slo_exemplar(request_id: str) -> Optional[dict]:
    """One full exemplar (ordered stage timeline + routing decision) by
    X-Request-Id, prefix ok."""
    return _cp().call("get_slo_exemplar", {"request_id": request_id},
                      timeout=10.0)


def list_events(kind: Optional[str] = None,
                severity: Optional[str] = None,
                entity: Optional[str] = None,
                since: Optional[float] = None,
                until: Optional[float] = None,
                limit: int = 100) -> list[dict]:
    """Flight-recorder journal (observability/events.py), newest first.
    `kind` filters exactly, `severity` is a minimum (WARNING hides
    INFO), `entity` substring-matches node/deployment/replica/request
    id/source, `since`/`until` are unix timestamps. The `ray-tpu events`
    CLI and the dashboard events panel render this."""
    body: dict[str, Any] = {"limit": limit}
    if kind:
        body["kind"] = kind
    if severity:
        body["severity"] = severity
    if entity:
        body["entity"] = entity
    if since is not None:
        body["since"] = since
    if until is not None:
        body["until"] = until
    return _cp().call("list_events", body, timeout=10.0) or []


def events_postmortem(window_s: float = 300.0,
                      until: Optional[float] = None) -> dict:
    """One ordered incident timeline for the trailing window: journal
    events + SLO-violation exemplars + per-series metric spike
    summaries, merged by timestamp (`ray-tpu events --postmortem`)."""
    body: dict[str, Any] = {"window_s": window_s}
    if until is not None:
        body["until"] = until
    return _cp().call("events_postmortem", body, timeout=15.0) or {
        "since": 0.0, "until": 0.0, "window_s": window_s, "items": []}


def kv_tier_gc() -> dict:
    """Drop expired kv_tier index entries (owners retract their own on
    demotion/shutdown; this sweeps entries whose owner is wedged).
    Returns {"dropped": n}."""
    return _cp().call("kv_tier_gc", {}, timeout=30.0) or {"dropped": 0}
