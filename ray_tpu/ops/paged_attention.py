"""Fused paged-attention kernel family (Pallas TPU) for the serving path.

The serving engine's gather attention materializes each slot's full
[max_len, Hkv, D] K/V view from the page pool every layer of every step
(~17 GB/step of HBM traffic for a 1.2B model at B=32 — see
serve/llm/kv_cache.py). These kernels read the pool pages DIRECTLY via
the slot page table (scalar-prefetch block index maps, the canonical
TPU paged-attention pattern): the per-slot view is assembled page by
page in VMEM scratch, never in HBM.

One core kernel covers the whole family — decode (T=1), multi-query
speculative verify (T=k+1 causal within the span), and chunked prefill
(B=1, extra ``true_len`` bound) are the same computation with different
query spans and masks, dispatched through thin wrappers.

Identity contract: greedy TOKENS under the pallas backend must equal the
gather backend exactly (hard-asserted in tests and the serve bench), so
the kernel computes the SAME dense-softmax numerics as the gather path —
fp32 logits scaled by ``sm_scale``, masked with -1e30, full-row fp32
softmax, probabilities cast back to q.dtype, same contractions — instead
of a flash-style streaming softmax (whose rescaling visibly changes
float results). Raw attention outputs agree with gather to the last ULPs
(the fused [R, L] dot and the batched einsum may order partial sums
differently); the win is memory traffic, not math: pages stream
HBM->VMEM once per (slot, kv-head) with no materialized gather
intermediate.

Off-TPU the kernels run in interpreter mode (pl.pallas_call
(interpret=True)), which is how tier-1 gates them on CPU — same story as
ops/attention.py.

Tensor parallelism (ISSUE 20): a pallas_call is opaque to GSPMD, so on a
TP mesh the serving engine runs these kernels under ``shard_map`` with
the canonical per-KV-head partitioning from :func:`tp_shard_specs` —
pool axis 0 (Hkv) and q's H axis split by the "tensor" mesh axis. The
kv-major GQA head order above is what makes that split clean: each
shard's kernel invocation is exactly a single-chip call over Hkv/tp
kv heads with their n_rep q heads, no kernel-internal changes and no
in-kernel collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30
# jax renamed TPUCompilerParams -> CompilerParams across versions
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams",
                           getattr(pltpu, "TPUCompilerParams", None))


def tp_shard_specs(q_rank: int, n_replicated: int, axis: str = "tensor"):
    """Canonical ``shard_map`` partition specs for this kernel family on a
    tensor-parallel mesh.

    Operand order is the family's wrapper signature: ``(q, k_pages,
    v_pages, <n_replicated trailing operands>)`` — page tables and scalar
    position/length operands are replicated. q of rank ``q_rank`` is split
    on its H axis (second-to-last); the pools on axis 0 (Hkv). Because
    ``paged_attention`` derives ``hkv``/``n_rep`` from operand shapes and
    splits heads kv-major, each shard's launch is a self-consistent
    single-chip call over its Hkv/tp kv-head groups.

    Returns ``(in_specs, out_spec)``; the output follows q's split.
    """
    q_spec = P(*([None] * (q_rank - 2) + [axis, None]))
    in_specs = (q_spec, P(axis), P(axis)) + (P(),) * n_replicated
    return in_specs, q_spec


def _paged_attn_kernel(pt_ref, base_ref, limit_ref,     # scalar prefetch
                       q_ref, k_ref, v_ref, o_ref, k_scr, v_scr, *,
                       sm_scale: float, page_size: int, num_pages: int,
                       t_span: int):
    """Grid (B, Hkv, num_pages); one (slot, kv-head) pair accumulates its
    pages into VMEM scratch and computes dense attention on the last page.

    q_ref: [1, 1, R, D] where R = n_rep * t_span, row r = rep * t_span + t
    (GQA heads grouped per kv head, query positions innermost — matches
    ``_gqa_expand``'s kv-major head order). k_ref/v_ref: this grid step's
    pool page [1, 1, page, D], selected by the block index map through the
    scalar-prefetched page table — the read IS the gather.
    """
    b = pl.program_id(0)
    p = pl.program_id(2)

    k_scr[pl.ds(p * page_size, page_size)] = k_ref[0, 0]
    v_scr[pl.ds(p * page_size, page_size)] = v_ref[0, 0]

    @pl.when(p == num_pages - 1)
    def _compute():
        q = q_ref[0, 0]                                       # [R, D]
        # q.dtype contraction then fp32 scale — exactly the gather path's
        # einsum(...).astype(f32) * sm
        s = jax.lax.dot_general(
            q, k_scr[:], (((1,), (1,)), ((), ())))            # [R, L]
        s = s.astype(jnp.float32) * sm_scale
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % t_span
        pos = base_ref[b] + t
        valid = (col <= pos) & (col < limit_ref[b])
        s = jnp.where(valid, s, _NEG_INF)
        w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        o_ref[0, 0] = jax.lax.dot_general(
            w, v_scr[:], (((1,), (0,)), ((), ())))            # [R, D]


def paged_attention(q, k_pages, v_pages, page_tables, base, limit=None, *,
                    sm_scale: float | None = None,
                    interpret: bool | None = None):
    """Fused paged attention over the whole query span.

    q: [B, T, H, D] — query position of q[:, t] is ``base + t`` (causal
    within the span, full attention over the paged cache below it).
    k_pages/v_pages: [Hkv, P, page, D] pool. page_tables: [B, max_pages].
    base: [B] int32 first-query positions. limit: [B] int32 exclusive key
    bound (None = the whole table span) — chunked prefill passes
    ``true_len`` so padded tail pages stay masked.
    Returns [B, T, H, D] in q.dtype.
    """
    b, t, h, d = q.shape
    hkv = k_pages.shape[0]
    n_rep = h // hkv
    page_size = k_pages.shape[2]
    max_pages = page_tables.shape[1]
    max_len = max_pages * page_size
    if sm_scale is None:
        sm_scale = d ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if limit is None:
        limit = jnp.full((b,), max_len, jnp.int32)
    r = n_rep * t
    # [B, T, H, D] -> [B, Hkv, n_rep*T, D]: kv-major head split (matches
    # _gqa_expand), query positions innermost so the kernel recovers t as
    # row % t_span
    qg = q.reshape(b, t, hkv, n_rep, d).transpose(0, 2, 3, 1, 4).reshape(
        b, hkv, r, d)

    kernel = functools.partial(
        _paged_attn_kernel, sm_scale=sm_scale, page_size=page_size,
        num_pages=max_pages, t_span=t)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, hkv, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, r, d),
                             lambda bi, hi, pi, pt, bs, lim: (bi, hi, 0, 0)),
                # the paged read: block index pt[bi, pi] picks the pool
                # page straight off the scalar-prefetched table
                pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, hi, pi, pt, bs, lim:
                             (hi, pt[bi, pi], 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, hi, pi, pt, bs, lim:
                             (hi, pt[bi, pi], 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, r, d),
                lambda bi, hi, pi, pt, bs, lim: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((max_len, d), k_pages.dtype),
                pltpu.VMEM((max_len, d), v_pages.dtype),
            ]),
        out_shape=jax.ShapeDtypeStruct((b, hkv, r, d), q.dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), base.astype(jnp.int32),
      limit.astype(jnp.int32), qg, k_pages, v_pages)
    return out.reshape(b, hkv, n_rep, t, d).transpose(0, 3, 1, 2, 4).reshape(
        b, t, h, d)


def paged_decode_attention(q, k_pages, v_pages, page_tables, pos, *,
                           sm_scale: float | None = None,
                           interpret: bool | None = None):
    """Single-token decode attention: q [B, H, D], new token at position
    ``pos[b]`` (attends 0..pos inclusive — its own k/v is already written
    to the pool). Returns [B, H, D]."""
    out = paged_attention(q[:, None], k_pages, v_pages, page_tables, pos,
                          sm_scale=sm_scale, interpret=interpret)
    return out[:, 0]


def paged_verify_attention(q, k_pages, v_pages, page_tables, seq_lens, *,
                           sm_scale: float | None = None,
                           interpret: bool | None = None):
    """Multi-query speculative verify: q [B, T, H, D], T = k+1 draft span
    per slot, q[b, t] at position ``seq_lens[b] + t`` — causal within the
    span, full attention over the slot's cached pages (all T spans' k/v
    are pre-written). Returns [B, T, H, D]."""
    return paged_attention(q, k_pages, v_pages, page_tables, seq_lens,
                           sm_scale=sm_scale, interpret=interpret)


def paged_chunk_attention(q, k_pages, v_pages, page_table, start, true_len,
                          *, sm_scale: float | None = None,
                          interpret: bool | None = None):
    """Chunked-prefill attention for ONE slot: q [1, C, H, D] chunk whose
    first token sits at position ``start``; keys are the slot's whole
    paged view (earlier chunks + this one, pre-written) bounded by
    ``true_len``. Returns [1, C, H, D]."""
    base = jnp.reshape(start, (1,)).astype(jnp.int32)
    limit = jnp.reshape(true_len, (1,)).astype(jnp.int32)
    return paged_attention(q, k_pages, v_pages, page_table[None], base,
                           limit, sm_scale=sm_scale, interpret=interpret)
