"""Instance lifecycle manager — the autoscaler v2 reconciliation model.

TPU-native analog of the reference's v2 instance manager
(python/ray/autoscaler/v2/instance_manager/ + instance_manager.proto:243):
every provider node is tracked as an Instance walking an explicit state
machine, with a recorded transition history the dashboard/operators can
audit:

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                   |            |            |
                   v            v            v
          ALLOCATION_FAILED  TERMINATING -> TERMINATED

The autoscaling loop makes decisions (launch N, terminate X); the
instance manager owns the provider calls and the truth about where each
instance is in its lifecycle, reconciling desired state against what the
provider and the control plane actually report each tick.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import time
import uuid
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class InstanceState(enum.Enum):
    QUEUED = "QUEUED"                  # decision made, provider not called
    REQUESTED = "REQUESTED"            # provider.create_node in flight
    ALLOCATED = "ALLOCATED"            # provider created; agents booting
    RAY_RUNNING = "RAY_RUNNING"        # every host registered with the CP
    ALLOCATION_FAILED = "ALLOCATION_FAILED"
    TERMINATING = "TERMINATING"        # provider.terminate_node issued
    TERMINATED = "TERMINATED"


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_config: dict
    state: InstanceState = InstanceState.QUEUED
    name: Optional[str] = None         # provider node name once allocated
    created_at: float = dataclasses.field(default_factory=time.time)
    updated_at: float = dataclasses.field(default_factory=time.time)
    history: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"instance_id": self.instance_id, "state": self.state.value,
                "name": self.name, "created_at": self.created_at,
                "updated_at": self.updated_at,
                "history": [(t, a, b, why) for t, a, b, why in self.history]}


class InstanceManager:
    """Owns provider calls + per-instance state transitions. Not
    thread-safe by itself — the autoscaling loop is the single driver
    (matching the reference's single reconciler)."""

    _MAX_TERMINAL = 64  # retained terminal records (audit window)

    def __init__(self, provider):
        self._provider = provider
        self._instances: dict[str, Instance] = {}

    # ---- queries -------------------------------------------------------
    def instances(self, states: Optional[set] = None) -> list[Instance]:
        out = [i for i in self._instances.values()
               if states is None or i.state in states]
        return sorted(out, key=lambda i: i.created_at)

    def active(self) -> list[Instance]:
        return self.instances({InstanceState.QUEUED, InstanceState.REQUESTED,
                               InstanceState.ALLOCATED,
                               InstanceState.RAY_RUNNING})

    def by_name(self, name: str) -> Optional[Instance]:
        """Newest NON-TERMINAL instance with this provider name: providers
        reuse names, and a retained TERMINATED audit record must never
        shadow the live instance."""
        matches = [i for i in self._instances.values() if i.name == name
                   and i.state not in (InstanceState.TERMINATED,
                                       InstanceState.ALLOCATION_FAILED)]
        return max(matches, key=lambda i: i.created_at, default=None)

    def summary(self) -> dict:
        out: dict[str, int] = {}
        for i in self._instances.values():
            out[i.state.value] = out.get(i.state.value, 0) + 1
        return out

    # ---- transitions ---------------------------------------------------
    _MAX_HISTORY = 50  # per-instance transition records (retry loops cap)

    def _transition(self, inst: Instance, to: InstanceState,
                    reason: str) -> None:
        inst.history.append((time.time(), inst.state.value, to.value, reason))
        if len(inst.history) > self._MAX_HISTORY:
            # keep creation + the most recent window (a provider outage
            # retrying every tick must not grow this unboundedly)
            inst.history = inst.history[:1] + \
                inst.history[-(self._MAX_HISTORY - 1):]
        logger.info("instance %s: %s -> %s (%s)", inst.instance_id[:8],
                    inst.state.value, to.value, reason)
        inst.state = to
        inst.updated_at = time.time()

    def queue_launch(self, node_config: dict) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex,
                        node_config=dict(node_config))
        inst.history.append((time.time(), None, "QUEUED", "launch decision"))
        self._instances[inst.instance_id] = inst
        return inst

    def launch(self, node_config: dict) -> Instance:
        """Queue + immediately drive the provider create (the common
        launch path; a full reconcile per launch would re-walk every
        tracked instance for nothing)."""
        inst = self.queue_launch(node_config)
        self._request(inst)
        return inst

    def _request(self, inst: Instance) -> None:
        self._transition(inst, InstanceState.REQUESTED, "provider create")
        try:
            inst.name = self._provider.create_node(inst.node_config)
            self._transition(inst, InstanceState.ALLOCATED,
                             f"provider node {inst.name}")
        except Exception as e:  # noqa: BLE001
            self._transition(inst, InstanceState.ALLOCATION_FAILED, repr(e))

    def begin_terminate(self, name: str, reason: str) -> bool:
        """Issue the provider terminate for a named instance; returns False
        when the provider call fails (the caller retries next tick)."""
        inst = self.by_name(name)
        if inst is None:
            inst = self._adopt(name)
        prior = inst.state
        self._transition(inst, InstanceState.TERMINATING, reason)
        try:
            self._provider.terminate_node(name)
        except Exception as e:  # noqa: BLE001 — provider flake: retry later
            # roll back to the ACTUAL prior state so the audit log never
            # fabricates a lifecycle stage the node didn't reach
            self._transition(inst, prior, f"terminate failed: {e!r}")
            return False
        return True

    def _adopt(self, name: str) -> Instance:
        """Track a provider node launched outside this manager (process
        restart, pre-manager launches)."""
        inst = Instance(instance_id=uuid.uuid4().hex, node_config={},
                        state=InstanceState.ALLOCATED, name=name)
        inst.history.append((time.time(), None, "ALLOCATED", "adopted"))
        self._instances[inst.instance_id] = inst
        return inst

    # ---- reconciliation ------------------------------------------------
    def reconcile(self, ray_running: Callable[[str], bool]) -> None:
        """One tick: push QUEUED into the provider, observe ALLOCATED →
        RAY_RUNNING via the CP view, TERMINATING → TERMINATED via the
        provider view. Boot-time policy (grace windows) stays with the
        autoscaler — the manager only records truth."""
        provider_nodes = set(self._provider.non_terminated_nodes())
        # adopt provider nodes this manager doesn't know (process restart):
        # "every provider node is tracked" must hold from the first tick
        known = {i.name for i in self._instances.values()
                 if i.name is not None and i.state not in
                 (InstanceState.TERMINATED, InstanceState.ALLOCATION_FAILED)}
        for name in provider_nodes - known:
            self._adopt(name)
        for inst in list(self._instances.values()):
            if inst.state == InstanceState.QUEUED:
                self._request(inst)
            elif inst.state == InstanceState.ALLOCATED:
                if inst.name not in provider_nodes:
                    self._transition(inst, InstanceState.ALLOCATION_FAILED,
                                     "vanished from provider while booting")
                elif ray_running(inst.name):
                    self._transition(inst, InstanceState.RAY_RUNNING,
                                     "all hosts registered")
                # NOTE deliberately no boot-grace kill here: slow multi-host
                # slice boots are the AUTOSCALER's policy call (it merely
                # stops counting them against demand); killing would churn
                # launch->partial-register->kill forever on slow slices
            elif inst.state == InstanceState.RAY_RUNNING:
                if inst.name not in provider_nodes:
                    self._transition(inst, InstanceState.TERMINATED,
                                     "gone from provider")
            elif inst.state == InstanceState.TERMINATING:
                if inst.name not in provider_nodes:
                    self._transition(inst, InstanceState.TERMINATED,
                                     "provider confirmed")
                else:
                    # the terminate call may have flaked mid-flight
                    # earlier: re-issue (idempotent on real providers)
                    try:
                        self._provider.terminate_node(inst.name)
                    except Exception:  # noqa: BLE001 — retry next tick
                        pass
        self._prune_terminal()

    def _prune_terminal(self) -> None:
        terminal = [i for i in self.instances(
            {InstanceState.TERMINATED, InstanceState.ALLOCATION_FAILED})]
        for inst in terminal[:-self._MAX_TERMINAL]:
            self._instances.pop(inst.instance_id, None)
