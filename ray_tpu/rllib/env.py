"""Environments for the RL library.

The reference's RLlib runs arbitrary gym envs on CPU rollout workers
(/root/reference/rllib/env/single_agent_env_runner.py). This build ships the
same Env protocol plus built-in numpy envs so the library is testable with
zero extra dependencies; any gymnasium env also satisfies the protocol.

Envs are host-side (numpy) by design: rollouts are branchy and sequential —
wrong shape for the MXU — so they stay on CPU actors while learning runs as a
jitted SPMD step on the accelerator (see learner.py).
"""

from __future__ import annotations

import numpy as np


class Env:
    """Minimal single-agent env protocol (gymnasium-compatible subset)."""

    observation_dim: int
    num_actions: int

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> tuple[np.ndarray, float, bool, bool]:
        """Returns (obs, reward, terminated, truncated)."""
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing (dynamics per Barto-Sutton-Anderson).

    Pure numpy so EnvRunner actors need no gym install; matches gymnasium's
    CartPole-v1 termination (|x|>2.4, |theta|>12deg, 500-step truncation).
    """

    observation_dim = 4
    num_actions = 2

    def __init__(self, max_steps: int = 500):
        self._max_steps = max_steps
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, np.float32)
        self._t = 0

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._t = 0
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, th, th_dot = self._state
        force = 10.0 if action == 1 else -10.0
        costh, sinth = np.cos(th), np.sin(th)
        # masscart 1.0, masspole 0.1, pole half-length 0.5, dt 0.02
        temp = (force + 0.05 * th_dot**2 * sinth) / 1.1
        th_acc = (9.8 * sinth - costh * temp) / (0.5 * (4.0 / 3.0 - 0.1 * costh**2 / 1.1))
        x_acc = temp - 0.05 * th_acc * costh / 1.1
        x, x_dot = x + 0.02 * x_dot, x_dot + 0.02 * x_acc
        th, th_dot = th + 0.02 * th_dot, th_dot + 0.02 * th_acc
        self._state = np.array([x, x_dot, th, th_dot], np.float32)
        self._t += 1
        terminated = bool(abs(x) > 2.4 or abs(th) > 0.2095)
        truncated = self._t >= self._max_steps
        return self._state.copy(), 1.0, terminated, truncated


class RandomWalk(Env):
    """1-D chain: start in the middle, +1 reward at the right end.

    Deliberately trivial — DQN/PPO must solve it in seconds, which keeps CI
    assertions about *learning* (not just running) cheap.
    """

    num_actions = 2

    def __init__(self, n: int = 9):
        self._n = n
        self.observation_dim = n
        self._pos = n // 2

    def reset(self, seed: int | None = None) -> np.ndarray:
        self._pos = self._n // 2
        return self._obs()

    def _obs(self) -> np.ndarray:
        o = np.zeros(self._n, np.float32)
        o[self._pos] = 1.0
        return o

    def step(self, action: int):
        self._pos += 1 if action == 1 else -1
        if self._pos <= 0:
            return self._obs(), 0.0, True, False
        if self._pos >= self._n - 1:
            return self._obs(), 1.0, True, False
        return self._obs(), 0.0, False, False


_REGISTRY = {"CartPole": CartPole, "RandomWalk": RandomWalk}


def register_env(name: str, creator) -> None:
    """(ref: rllib tune.register_env) — creator() -> Env."""
    _REGISTRY[name] = creator


def make_env(spec) -> Env:
    if isinstance(spec, Env):
        return spec
    if callable(spec):
        return spec()
    if isinstance(spec, str) and spec in _REGISTRY:
        return _REGISTRY[spec]()
    raise ValueError(f"unknown env: {spec!r} (register_env or pass a creator)")


def resolve_env_spec(spec):
    """Resolve a registry name to its creator on the driver, so the spec
    shipped to EnvRunner actors (other processes, which only have the
    builtin registry) is self-contained."""
    if isinstance(spec, str) and spec in _REGISTRY:
        return _REGISTRY[spec]
    return spec
