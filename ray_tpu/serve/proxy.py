"""HTTP ingress proxy.

TPU-native analog of the reference's proxy
(/root/reference/python/ray/serve/_private/proxy.py — HTTPProxy:706,
proxy_request:414, send_request_to_replica:886): an aiohttp server that
resolves the route prefix to an application's ingress deployment, routes via
the pow-2 router, and returns the replica's response. JSON in/out; the
reference's full ASGI passthrough is out of scope for the HTTP layer v1 —
deployments see a dict request body.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

import ray_tpu
from ray_tpu.serve.router import Router


class HTTPProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self.host = host
        self.port = port
        self._routers: dict[str, Router] = {}
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._runner = None

    # ---- lifecycle -----------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._serve_thread,
                                        daemon=True, name="http_proxy")
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("http proxy failed to start")

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _serve_thread(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        self._runner = runner
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(runner.cleanup())
            loop.close()

    # ---- request path --------------------------------------------------
    async def _resolve_route(self, path: str):
        routes = await _aget(self._controller.get_http_routes.remote())
        best = None
        for prefix, target in routes.items():
            if prefix is None:
                continue
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") \
                    or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, target)
        return best

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info.get("tail", "")
        if path == "/-/routes":
            routes = await _aget(self._controller.get_http_routes.remote())
            return web.json_response(
                {p: f"{a}#{d}" for p, (a, d) in routes.items()})
        if path == "/-/healthz":
            return web.Response(text="ok")

        resolved = await self._resolve_route(path)
        if resolved is None:
            return web.Response(status=404, text=f"no route for {path}")
        prefix, (app_name, deployment) = resolved

        router = self._routers.get(app_name)
        if router is None:
            router = Router(self._controller, app_name)
            self._routers[app_name] = router

        # build the request payload the user callable sees
        body = await request.read()
        payload: object
        if body:
            try:
                payload = json.loads(body)
            except json.JSONDecodeError:
                payload = body
        else:
            payload = dict(request.query)

        try:
            ref = await asyncio.get_event_loop().run_in_executor(
                None, lambda: router.assign(
                    deployment, "__call__", (payload,), {}))
            result = await _aget(ref)
        except TimeoutError as e:
            return web.Response(status=503, text=str(e))
        except Exception as e:  # noqa: BLE001 - surface replica errors as 500
            return web.Response(status=500, text=repr(e))

        if isinstance(result, (bytes, bytearray)):
            return web.Response(body=bytes(result))
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)


async def _aget(ref):
    loop = asyncio.get_event_loop()
    return await loop.run_in_executor(None, lambda: ray_tpu.get(ref))
