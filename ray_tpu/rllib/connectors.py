"""Connector pipelines — composable batch transforms between env, module,
and learner.

TPU-native analog of the reference connector stack (rllib/connectors/ —
ConnectorV2 with env-to-module, module-to-env, and learner pipelines):
each connector is a pure callable over the COLUMN BATCH dicts this
runtime's env runners and algorithms already speak, so custom
preprocessing/postprocessing composes into any algorithm without
subclassing it. Stateful connectors (the running obs filter) expose
get_state/set_state so runner-side copies can be synced from the learner
(reference: connector state in the learner group).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


class Connector:
    """One transform stage (reference ConnectorV2). ``__call__`` receives a
    dict batch (or a single observation array for env-to-module use) and
    returns the transformed value."""

    def __call__(self, batch: Any) -> Any:
        raise NotImplementedError

    # stateful connectors override; stateless return None/ignore
    def get_state(self) -> Optional[dict]:
        return None

    def set_state(self, state: Optional[dict]) -> None:
        pass

    def merge_states(self, states: list):
        """Combine per-runner states into one (driver-side sync each
        iteration). Default: first non-None wins (stateless/unmergeable)."""
        return next((s for s in states if s is not None), None)

    def frozen(self, batch: Any) -> Any:
        """Apply WITHOUT mutating running statistics (evaluation path)."""
        return self(batch)

    def reset(self) -> None:
        """Episode boundary (e.g. FrameStack clears its window)."""


class ConnectorPipeline(Connector):
    """Ordered composition of connectors (reference pipeline semantics)."""

    def __init__(self, connectors: list):
        self.connectors = list(connectors)

    def __call__(self, batch: Any) -> Any:
        for c in self.connectors:
            batch = c(batch)
        return batch

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: Optional[dict]) -> None:
        for i, c in enumerate(self.connectors):
            if state and state.get(i) is not None:
                c.set_state(state[i])

    def merge_states(self, states: list) -> dict:
        return {i: c.merge_states([st.get(i) if st else None
                                   for st in states])
                for i, c in enumerate(self.connectors)}

    def frozen(self, batch: Any) -> Any:
        for c in self.connectors:
            batch = c.frozen(batch)
        return batch

    def reset(self) -> None:
        for c in self.connectors:
            c.reset()

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self


# ---------------------------------------------------------------------------
# env-to-module (observation preprocessing)
# ---------------------------------------------------------------------------

class MeanStdFilter(Connector):
    """Running mean/std observation normalization (reference
    connectors/env_to_module/mean_std_filter.py). Welford accumulation;
    the update can be frozen (evaluation) and state synced across
    runners."""

    def __init__(self, shape: tuple, clip: float = 10.0,
                 update: bool = True):
        self._mean = np.zeros(shape, np.float64)
        self._m2 = np.zeros(shape, np.float64)
        self._count = 1e-4
        # DELTA accumulator: samples seen since the last get_state harvest.
        # Sync merges deltas into the global filter and broadcasts totals —
        # re-merging absolute states would double-count the shared base
        # every iteration (the reference filter keeps the same split).
        self._d_mean = np.zeros(shape, np.float64)
        self._d_m2 = np.zeros(shape, np.float64)
        self._d_count = 0.0
        self._clip = clip
        self.update_enabled = update

    def __setstate__(self, state):
        # unpickling via the object plane hands back READ-ONLY zero-copy
        # array views; the Welford accumulators mutate in place
        self.__dict__.update(state)
        for name in ("_mean", "_m2", "_d_mean", "_d_m2"):
            setattr(self, name, np.array(getattr(self, name)))

    def __call__(self, obs):
        arr = np.asarray(obs, np.float64)
        rows = arr if arr.ndim > self._mean.ndim else arr[None]
        if self.update_enabled:
            for row in rows:
                self._count += 1.0
                d = row - self._mean
                self._mean += d / self._count
                self._m2 += d * (row - self._mean)
                self._d_count += 1.0
                dd = row - self._d_mean
                self._d_mean += dd / self._d_count
                self._d_m2 += dd * (row - self._d_mean)
        std = np.sqrt(self._m2 / self._count) + 1e-8
        out = np.clip((arr - self._mean) / std, -self._clip, self._clip)
        return out.astype(np.float32)

    def get_state(self) -> dict:
        """Snapshot totals AND harvest the since-last-sync delta (the
        delta accumulator clears — sync consumes it exactly once)."""
        state = {"mean": self._mean.copy(), "m2": self._m2.copy(),
                 "count": self._count,
                 "delta": {"mean": self._d_mean.copy(),
                           "m2": self._d_m2.copy(),
                           "count": self._d_count}}
        self._d_mean = np.zeros_like(self._d_mean)
        self._d_m2 = np.zeros_like(self._d_m2)
        self._d_count = 0.0
        return state

    def set_state(self, state: Optional[dict]) -> None:
        if state:
            self._mean = np.asarray(state["mean"], np.float64).copy()
            self._m2 = np.asarray(state["m2"], np.float64).copy()
            self._count = float(state["count"])

    def merge_states(self, states: list):
        """Combine harvested runner DELTAS into this (driver) filter's
        totals via parallel Welford; returns the new totals to broadcast."""
        count, mean, m2 = self._count, self._mean.copy(), self._m2.copy()
        for s in states:
            if not s:
                continue
            d_state = s.get("delta") or s
            c2 = float(d_state["count"])
            if c2 <= 0:
                continue
            mu2 = np.asarray(d_state["mean"], np.float64)
            m22 = np.asarray(d_state["m2"], np.float64)
            d = mu2 - mean
            tot = count + c2
            m2 = m2 + m22 + d * d * count * c2 / tot
            mean = mean + d * c2 / tot
            count = tot
        return {"mean": mean, "m2": m2, "count": count}

    def frozen(self, obs):
        prev = self.update_enabled
        self.update_enabled = False
        try:
            return self(obs)
        finally:
            self.update_enabled = prev


class FrameStack(Connector):
    """Stack the last N observations along the feature axis (reference
    frame-stacking env-to-module connector). Call reset() at episode
    boundaries."""

    def __init__(self, shape: tuple, n: int = 4):
        self._n = n
        self._shape = tuple(shape)
        self._frames = [np.zeros(self._shape, np.float32)
                        for _ in range(n)]

    def reset(self) -> None:
        self._frames = [np.zeros(self._shape, np.float32)
                        for _ in range(self._n)]

    def __call__(self, obs):
        self._frames.pop(0)
        self._frames.append(np.asarray(obs, np.float32))
        return np.concatenate(self._frames, axis=-1)


class FlattenObs(Connector):
    """Flatten structured observations to one vector (reference
    flatten_observations connector)."""

    def __call__(self, obs):
        if isinstance(obs, dict):
            return np.concatenate(
                [np.asarray(obs[k], np.float32).ravel()
                 for k in sorted(obs)])
        return np.asarray(obs, np.float32).ravel()


# ---------------------------------------------------------------------------
# learner pipeline (batch transforms before the update)
# ---------------------------------------------------------------------------

class ClipRewards(Connector):
    """Clip batch rewards into [-limit, limit] (reference learner-side
    reward clipping)."""

    def __init__(self, limit: float = 1.0):
        self._limit = limit

    def __call__(self, batch: dict) -> dict:
        out = dict(batch)
        out["rewards"] = np.clip(batch["rewards"], -self._limit, self._limit)
        return out


class StandardizeFields(Connector):
    """Zero-mean/unit-std selected batch columns (the reference's
    advantage standardization as a connector)."""

    def __init__(self, fields: list):
        self._fields = list(fields)

    def __call__(self, batch: dict) -> dict:
        out = dict(batch)
        for f in self._fields:
            v = np.asarray(batch[f], np.float32)
            out[f] = (v - v.mean()) / (v.std() + 1e-8)
        return out
