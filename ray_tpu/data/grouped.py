"""GroupedData: groupby().agg/count/sum/... (reference:
/root/reference/python/ray/data/grouped_data.py)."""

from __future__ import annotations

from typing import Callable

from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data.logical import Aggregate, MapBatches


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def aggregate(self, *aggs) -> "Dataset":
        return self._ds._with(Aggregate(
            name=f"Aggregate({self._key})", inputs=[self._ds._terminal],
            key=self._key, aggs=list(aggs)))

    agg = aggregate

    def count(self):
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof))

    def map_groups(self, fn: Callable) -> "Dataset":
        """Apply fn to each group (runs after a sort-by-key repartition)."""
        key = self._key

        def apply(batch: dict):
            import numpy as np

            from ray_tpu.data.block import BlockAccessor, block_from_rows
            keys = batch[key]
            order = np.argsort(keys, kind="stable")
            sorted_keys = keys[order]
            uniq, starts = np.unique(sorted_keys, return_index=True)
            outs = []
            for i in range(len(uniq)):
                lo = starts[i]
                hi = starts[i + 1] if i + 1 < len(starts) else len(sorted_keys)
                idx = order[lo:hi]
                group = {k: v[idx] for k, v in batch.items()}
                res = fn(group)
                outs.append(BlockAccessor.batch_to_block(res))
            return BlockAccessor.concat(outs)

        # repartition by key hash so each group lands wholly in one block
        from ray_tpu.data.dataset import Dataset
        from ray_tpu.data.logical import Repartition
        ds = self._ds.sort(self._key)
        return ds.map_batches(apply, batch_format="numpy")
