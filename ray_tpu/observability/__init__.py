"""Observability subsystems: distributed tracing (tracing.py) and
performance introspection — engine phase timers, compile-event tracking,
device-memory accounting, on-demand XProf capture, and the local
context-manager profiling helpers (profiling.py — ray_tpu.util.profiling
re-exports them for compatibility)."""
