"""Serve controller: target-state reconciliation for deployments.

TPU-native analog of the reference's ServeController
(/root/reference/python/ray/serve/_private/controller.py:95 —
run_control_loop:387; deployment_state.py replica lifecycle;
autoscaling_state.py; deployment_scheduler.py). A detached actor owns the
target state {app -> deployments -> config}, reconciles replica actors
toward it, health-checks them, applies queue-length autoscaling, and serves
versioned routing tables to routers/proxies (the long-poll analog).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Optional

import ray_tpu
from ray_tpu.exceptions import TaskError
from ray_tpu.observability import events as _fr
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import ServeReplica

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "_serve_controller"


class _DeploymentState:
    def __init__(self, app: str, name: str, serialized_cls, init_args,
                 init_kwargs, config: DeploymentConfig, route_prefix):
        self.app = app
        self.name = name
        self.serialized_cls = serialized_cls
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.route_prefix = route_prefix
        self.replicas: list = []
        # created but not yet past their first health check: NOT routable
        # (the reference's STARTING state) — requests must never queue
        # behind actor creation
        self.starting: list = []
        # replicas on a DRAINING node: still routable (their node keeps
        # running in-flight + new work until the drain deadline) but no
        # longer counted toward target, so reconcile pre-starts
        # replacements. Retired — table flip FIRST, then graceful stop —
        # only once enough replacements are ready.
        self.draining: list = []
        # ready (first health check passed) but still pre-populating their
        # prefix cache from the KV tier (ISSUE 17 cache-warm scale-up):
        # NOT routable. They join `replicas` — with the version bump in
        # the same synchronous block — only once the warm_start RPC
        # resolves, so the router's first sight of a scale-up replica is
        # a warm holder, never a cold one cratering the fleet hit rate.
        self.warming: list = []
        # in-flight warm_start tasks keyed by replica actor-id hex
        self._warm_tasks: dict = {}
        # cumulative warm-start economy across this deployment's scale-ups
        self.warm_stats: dict = {"replicas_warmed": 0, "pages": 0,
                                 "ms": 0.0}
        # signal-driven scale decision log (ISSUE 17): bounded ring of
        # {ts, from, to, reason, signals} plus per-reason counters —
        # exported through detailed_status for the dashboard and the
        # open-loop harness
        self.scale_decisions: list = []
        self.scale_counters: dict[str, int] = {}
        self._signals: dict = {}
        self._signals_ts = 0.0
        # True while the heat guard is continuously refusing a downscale
        # (so the refusal is logged once per episode, not per 0.2s tick)
        self._guard_episode = False
        self.version = 0
        self.target = config.target_replicas()
        # consecutive failed health checks per replica (actor id hex) — a
        # replica is dropped only at health_check_failure_threshold
        self.health_fails: dict[str, int] = {}
        # Prefix-affinity summaries (ISSUE 10): per-replica resident
        # page-chain digests, collected piggyback on the reconcile tick and
        # shipped to routers through the routing-table long-poll (the
        # request path stays RPC-free). Keyed by replica actor-id hex —
        # bounded by (replicas × prefix_summary_max_pages). A replaced
        # replica's entry is pruned the tick it leaves `replicas`, so it
        # starts cold in every router.
        self.summary_gen = 0
        self.summaries: dict[str, list] = {}
        self.summary_versions: dict[str, int] = {}
        self.summary_meta: dict = {}
        # replicas that answered "prefix cache off / not an engine": never
        # probed again (their entry is dropped if the actor is replaced)
        self.summary_unsupported: set[str] = set()
        self._last_scale_ts = 0.0
        self._scale_pending_since: Optional[float] = None
        self._pending_target: Optional[int] = None

    def full_name(self) -> str:
        return f"{self.app}#{self.name}"


@ray_tpu.remote
class ServeController:
    def __init__(self):
        self._deployments: dict[str, _DeploymentState] = {}
        self._routes: dict[str, tuple[str, str]] = {}  # prefix -> (app, deployment)
        self._stopped = False
        # __init__ runs off the actor event loop; the control loop is started
        # lazily from the first async method invocation.
        self._loop_task = None
        # node-death pubsub: the handler runs on the hosting worker's pubsub
        # dispatch thread; the control loop drains this on its own cadence
        self._dead_nodes: list = []
        self._draining_nodes: list = []
        self._dead_nodes_lock = threading.Lock()
        self._node_sub_done = False
        # affinity-summary collection cadence (ISSUE 10): piggybacks on
        # the 0.2s reconcile tick but only probes replicas this often
        self._summary_ts = 0.0
        self._summary_interval_s = 1.0

    def _ensure_started(self):
        if self._loop_task is None:
            self._loop_task = asyncio.ensure_future(self._control_loop())
            self._change_event = asyncio.Event()
            self._subscribe_node_events()

    def _subscribe_node_events(self):
        """Wire CP `node` pubsub death events into the reconcile loop so
        replicas on a dead node are replaced PROACTIVELY instead of waiting
        out health-check timeouts (ref: GcsActorManager::OnNodeDead)."""
        if self._node_sub_done:
            return
        self._node_sub_done = True
        try:
            from ray_tpu.core import api as _api
            rt = _api._try_get_runtime()
            if rt is not None:
                rt.register_pubsub_handler("node", self._on_node_event)
        except Exception:  # noqa: BLE001 — degraded: health checks still work
            logger.exception("serve controller: node pubsub wiring failed")

    def _on_node_event(self, msg):
        if not isinstance(msg, dict):
            return
        event = msg.get("event")
        if event not in ("dead", "draining"):
            return
        node_id = msg.get("node_id")
        hexed = node_id.hex() if hasattr(node_id, "hex") else str(node_id)
        with self._dead_nodes_lock:
            if event == "dead":
                self._dead_nodes.append(hexed)
            else:
                self._draining_nodes.append(hexed)

    def _notify_change(self):
        ev = getattr(self, "_change_event", None)
        if ev is not None:
            ev.set()
            self._change_event = asyncio.Event()

    # ---- deploy API ----------------------------------------------------
    async def deploy_application(self, app_name: str,
                                 deployments: list[dict]) -> bool:
        """deployments: [{name, serialized_cls, init_args, init_kwargs,
        config(DeploymentConfig), route_prefix, is_ingress}]"""
        self._ensure_started()
        new_names = set()
        for d in deployments:
            key = f"{app_name}#{d['name']}"
            new_names.add(key)
            existing = self._deployments.get(key)
            state = _DeploymentState(
                app_name, d["name"], d["serialized_cls"],
                d.get("init_args"), d.get("init_kwargs"),
                d["config"], d.get("route_prefix"))
            if existing is not None:
                state.replicas = existing.replicas
                state.starting = existing.starting
                state.draining = existing.draining
                state.warming = existing.warming
                state._warm_tasks = existing._warm_tasks
                state.warm_stats = existing.warm_stats
                state.scale_decisions = existing.scale_decisions
                state.scale_counters = existing.scale_counters
                # config change with same code → reconfigure in place
                if d["config"].user_config is not None:
                    for r in state.replicas:
                        try:
                            await asyncio.wait_for(_as_future(
                                r.reconfigure.remote(
                                    d["config"].user_config)), 10.0)
                        except Exception:  # noqa: BLE001
                            pass
                # version computed AT PUBLISH time, after the awaits above:
                # the control loop may bump existing.version while a
                # reconfigure is in flight, and republishing at an older
                # (or equal) version would leave long-pollers pinned on
                # the stale table forever (ISSUE 17 atomicity fix)
                state.version = existing.version + 1
            self._deployments[key] = state
            if d.get("is_ingress") and d.get("route_prefix") is not None:
                self._routes[d["route_prefix"]] = (app_name, d["name"])
        self._notify_change()
        # remove deployments of this app not in the new spec
        for key in [k for k in self._deployments
                    if k.startswith(app_name + "#") and k not in new_names]:
            await self._drain_deployment(self._deployments.pop(key))
        # wait until all deployments have their target replicas up
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(len(s.replicas) >= s.target
                   for s in self._deployments.values()
                   if s.app == app_name):
                return True
            await asyncio.sleep(0.05)
        return False

    async def delete_application(self, app_name: str) -> bool:
        self._ensure_started()
        for key in [k for k in self._deployments
                    if self._deployments[k].app == app_name]:
            await self._drain_deployment(self._deployments.pop(key))
        self._routes = {p: t for p, t in self._routes.items()
                        if t[0] != app_name}
        return True

    async def _drain_deployment(self, state: _DeploymentState):
        for t in state._warm_tasks.values():
            t.cancel()
        state._warm_tasks = {}
        for r in state.starting + state.warming:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        state.starting = []
        state.warming = []
        for r in state.replicas + state.draining:
            try:
                await asyncio.wait_for(
                    _as_future(r.prepare_for_shutdown.remote(
                        state.config.graceful_shutdown_timeout_s)),
                    state.config.graceful_shutdown_timeout_s + 5.0)
            except Exception:  # noqa: BLE001
                pass
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
        state.replicas = []
        state.draining = []

    # ---- introspection -------------------------------------------------
    def _summary_entry(self, state: _DeploymentState,
                       known_gen: Optional[int]) -> Optional[dict]:
        """The affinity-summary element of a routing-table entry. None when
        the router already holds this generation (delta shipping: an
        unchanged fleet costs zero summary bytes per poll). A deployment
        with nothing collected (non-LLM) still ships its empty gen-0
        entry until the router acknowledges the gen — withholding it
        would pin the router at gen -1, make every poll look changed,
        and degenerate the long-poll into a hot spin."""
        if known_gen is not None and known_gen == state.summary_gen:
            return None
        return {"gen": state.summary_gen,
                "meta": dict(state.summary_meta),
                "replicas": {k: list(v) for k, v in state.summaries.items()}}

    async def get_routing_table(self, app_name: str,
                                known_gens: Optional[dict] = None) -> dict:
        self._ensure_started()
        known_gens = known_gens or {}
        out = {}
        for state in self._deployments.values():
            if state.app == app_name:
                # draining replicas stay routable until replacements are
                # ready — the table never shrinks below target mid-drain.
                # (Their affinity summaries are ALREADY gone: the collector
                # prunes anything not in `replicas`, so draining replicas
                # take load-balanced spillover only, never affinity pulls.)
                out[state.name] = (list(state.replicas) + list(state.draining),
                                   state.version,
                                   self._summary_entry(
                                       state, known_gens.get(state.name)))
        return out

    async def poll_routing_table(self, app_name: str,
                                 known_versions: dict,
                                 timeout_s: float = 30.0) -> dict | None:
        """LONG-POLL (reference long_poll.py LongPollHost:228): returns the
        app's routing table as soon as any deployment's version OR affinity
        summary generation differs from `known_versions`
        ({name: version} or {name: [version, summary_gen]} — both accepted),
        or None at timeout. Routers hang on this instead of re-polling on a
        timer."""
        self._ensure_started()
        deadline = asyncio.get_event_loop().time() + timeout_s
        known: dict = {}
        known_gens: dict = {}
        for d, v in dict(known_versions or {}).items():
            if isinstance(v, (list, tuple)) and v:
                known[d] = v[0]
                # legacy single-int callers never subscribe to summaries
                known_gens[d] = v[1] if len(v) > 1 else None
            else:
                known[d] = v
        while True:
            states = [s for s in self._deployments.values()
                      if s.app == app_name]
            current = {s.name: s.version for s in states}
            # Changed = a deployment the router hasn't seen (or at an older
            # version), or a deployment the router saw a REAL version of that
            # is now gone. A router-side placeholder (version -1 for a
            # deployment that doesn't exist yet) must NOT count, or the
            # long-poll degenerates into a hot spin.
            changed = any(known.get(d) != ver for d, ver in current.items()) \
                or any(ver >= 0 and d not in current
                       for d, ver in known.items()) \
                or any(d in known_gens and known_gens[d] is not None
                       and known_gens[d] != s.summary_gen for d, s in
                       ((s.name, s) for s in states))
            if changed:
                return await self.get_routing_table(app_name, known_gens)
            ev = self._change_event
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                return None
            try:
                await asyncio.wait_for(ev.wait(), timeout=min(remaining, 5.0))
            except asyncio.TimeoutError:
                pass

    async def get_http_routes(self) -> dict:
        self._ensure_started()
        return dict(self._routes)

    async def get_request_timeout(self, app_name: str,
                                  deployment: str) -> Optional[float]:
        """Deployment's default end-to-end request timeout (None = fall back
        to the `serve_request_timeout_s` flag; proxy caches this)."""
        self._ensure_started()
        state = self._deployments.get(f"{app_name}#{deployment}")
        if state is None:
            return None
        return getattr(state.config, "request_timeout_s", None)

    async def get_slo_policy(self, app_name: str,
                             deployment: str) -> Optional[dict]:
        """Deployment's SLO policy for the proxy's critical-path
        attribution (None = unknown deployment; all-None values = no
        objectives configured, baseline sampling only)."""
        self._ensure_started()
        state = self._deployments.get(f"{app_name}#{deployment}")
        if state is None:
            return None
        return {
            "slo_ttft_p99_ms": getattr(state.config, "slo_ttft_p99_ms",
                                       None),
            "slo_e2e_p99_ms": getattr(state.config, "slo_e2e_p99_ms", None),
            "slo_sample_rate": getattr(state.config, "slo_sample_rate",
                                       0.01),
        }

    async def ingress_has_http_dispatch(self, app_name: str,
                                        deployment: str) -> bool:
        """Does the ingress class define handle_http(path, method, payload)?
        (Proxy sub-path dispatch for multi-route apps, e.g. the OpenAI
        ingress — ray_tpu.serve.llm.openai_api.)"""
        self._ensure_started()
        state = self._deployments.get(f"{app_name}#{deployment}")
        if state is None:
            return False
        import cloudpickle
        try:
            cls = cloudpickle.loads(state.serialized_cls)
        except Exception:  # noqa: BLE001
            return False
        return callable(getattr(cls, "handle_http", None))

    async def status(self) -> dict:
        self._ensure_started()
        return {
            state.full_name(): {
                "replicas": len(state.replicas),
                "draining": len(state.draining),
                "warming": len(state.warming),
                "target": state.target,
                "version": state.version,
                "app": state.app,
                "role": state.config.role,
            }
            for state in self._deployments.values()
        }

    async def detailed_status(self) -> dict:
        """status() plus live per-replica queue lengths (dashboard serve
        view; reference: dashboard/modules/serve/ deployment details)."""
        self._ensure_started()

        async def probe(replica):
            try:
                return int(await asyncio.wait_for(
                    replica.get_queue_len.remote(), timeout=2.0))
            except Exception:  # noqa: BLE001 — replica busy/dead
                return None

        # engine stats ride next to the live queue lens: deployments whose
        # callable defines engine_stats() (LLM servers) report steps /
        # prefills / tokens_out / shed counts / prefix-cache hit-miss-evict
        # counters per replica — plus the ISSUE-6 introspection surface
        # (per-phase p50/p95, ITL, compile events, device memory) that the
        # dashboard /profiling panel renders; anything else probes to None
        _ENGINE_KEYS = ("steps", "prefills", "tokens_out", "requests",
                        "shed_expired",
                        "active_slots", "waiting", "free_pages",
                        "failover_resumed", "failover_restored_tokens",
                        "prefix_hits", "prefix_misses", "prefix_hit_tokens",
                        "prefix_cached_pages", "prefix_shared_pages",
                        "prefix_evictions",
                        "spilled_pages", "restored_pages",
                        "restore_partial", "restoring",
                        "warm_start_pages", "warm_start_ms",
                        "disagg_prefills", "handoff_bytes_wire",
                        "handoff_overlap_ms",
                        "tier_hit_tokens", "tier_bytes_shm",
                        "tier_bytes_disk",
                        "tier_bytes_shm_raw", "tier_bytes_disk_raw",
                        "tier_codec_ratio",
                        "tier_encode_ms_p50", "tier_decode_ms_p50",
                        "tier_prefetch_hints", "tier_prefetch_pages",
                        "tier_prefetch_hit_pages",
                        "prefix_summary_version", "prefix_summary_pages",
                        "decode_block_effective", "pending_pipeline_depth",
                        "spec_rounds", "spec_drafted_tokens",
                        "spec_accepted_tokens",
                        "attention_backend", "attn_backend_pallas",
                        "attn_kernel_compiles", "attn_decode_dispatches",
                        "attn_verify_dispatches", "attn_chunk_dispatches",
                        "tp_degree", "mesh_shape", "kv_shard_pool_bytes",
                        "kv_shard_page_occupancy",
                        "itl_s", "compile_events", "mid_traffic_compiles",
                        "compile_s", "weights_bytes", "kv_pool_bytes",
                        "kv_page_occupancy", "device_bytes_in_use",
                        "device_peak_bytes") + tuple(
                            f"phase_{p}_{q}_ms"
                            for p in ("queue_wait", "admit", "prefill",
                                      "chunk_prefill", "decode_dispatch",
                                      "verify_dispatch", "harvest")
                            for q in ("p50", "p95"))

        async def probe_engine(replica):
            try:
                stats = await asyncio.wait_for(
                    replica.handle_request.remote("engine_stats", (), {}),
                    timeout=2.0)
            except Exception:  # noqa: BLE001 — not an engine / busy / dead
                return None
            if not isinstance(stats, dict):
                return None
            return {k: stats[k] for k in _ENGINE_KEYS if k in stats}

        out = {}
        for state in self._deployments.values():
            # concurrent probes: a deployment of N hung replicas must cost
            # one 2s timeout, not N of them (the dashboard polls this)
            qlens = list(await asyncio.gather(
                *(probe(r) for r in state.replicas)))
            engines = list(await asyncio.gather(
                *(probe_engine(r) for r in state.replicas)))
            out[state.full_name()] = {
                "app": state.app,
                "role": state.config.role,
                "replicas": len(state.replicas),
                "starting": len(state.starting),
                "warming": len(state.warming),
                "draining": len(state.draining),
                "target": state.target,
                "version": state.version,
                "queue_lens": qlens,
                "engine": (engines if any(e is not None for e in engines)
                           else None),
                "latency_ms": self._latency_percentiles(state.name),
                # elastic fleet (ISSUE 17): the scale-decision flight
                # recorder + cache-warm scale-up economy the dashboard
                # serve panel and the open-loop harness render
                "scale_decisions": list(state.scale_decisions[-10:]),
                "scale_counters": dict(state.scale_counters),
                "warm": dict(state.warm_stats),
                "signals": dict(state._signals),
            }
        return out

    @staticmethod
    def _latency_percentiles(deployment: str) -> dict | None:
        """p50/p95/p99 (ms) from the CP time-series store: the merged
        cross-replica cumulative histogram of on-replica processing latency
        (ISSUE 4 percentile views). None until the replicas' flushers have
        reported."""
        try:
            from ray_tpu.core import api as _api
            from ray_tpu.util.metrics import percentiles_from_buckets
            rt = _api._try_get_runtime()
            if rt is None:
                return None
            res = rt.cp_client.call(
                "metrics_query",
                {"name": "ray_tpu_serve_replica_processing_seconds",
                 "tags": {"deployment": deployment}}, timeout=5.0)
            merged = (res or {}).get("merged")
            if not merged or not merged.get("count"):
                return None
            qs = percentiles_from_buckets(
                res.get("boundaries") or [], merged["buckets"])
            return {f"p{round(q * 100)}": (None if v is None else v * 1000.0)
                    for q, v in qs.items()}
        except Exception:  # noqa: BLE001 — metrics are best-effort
            return None

    async def shutdown(self) -> bool:
        self._stopped = True
        for state in self._deployments.values():
            for t in state._warm_tasks.values():
                t.cancel()
            for r in (state.replicas + state.starting + state.warming
                      + state.draining):
                try:
                    ray_tpu.kill(r)
                except Exception:  # noqa: BLE001
                    pass
        self._deployments = {}
        return True

    # ---- reconciliation loop -------------------------------------------
    async def _control_loop(self):
        while not self._stopped:
            try:
                await self._reconcile_once()
            except Exception:  # noqa: BLE001
                logger.exception("serve control loop error")
            await asyncio.sleep(0.2)

    @staticmethod
    def _replica_key(replica) -> str:
        aid = getattr(replica, "_actor_id", None)
        return aid.hex() if hasattr(aid, "hex") else str(id(replica))

    async def _drop_replicas_on_dead_nodes(self):
        """Drain node-death events and immediately drop (and kill) replicas
        placed on those nodes — the reconcile pass below restarts
        replacements this same tick."""
        with self._dead_nodes_lock:
            dead, self._dead_nodes = list(self._dead_nodes), []
        if not dead:
            return
        dead_set = set(dead)

        def _list_actors_blocking():
            from ray_tpu.util import state as state_api
            return state_api.list_actors(limit=100000)

        try:
            actors = await asyncio.get_event_loop().run_in_executor(
                None, _list_actors_blocking)
        except Exception:  # noqa: BLE001 — CP briefly away; health checks
            logger.exception("list_actors failed while handling node death")
            return
        on_dead_nodes = {a["actor_id"] for a in actors
                         if a.get("node_id") in dead_set}
        for state in self._deployments.values():
            keep = [r for r in state.replicas
                    if self._replica_key(r) not in on_dead_nodes]
            if len(keep) != len(state.replicas):
                lost = len(state.replicas) - len(keep)
                logger.warning(
                    "%s: %d replica(s) on dead node(s) %s — replacing",
                    state.full_name(), lost,
                    [n[:8] for n in dead_set])
                for r in state.replicas:
                    if self._replica_key(r) in on_dead_nodes:
                        state.health_fails.pop(self._replica_key(r), None)
                        try:
                            ray_tpu.kill(r)  # idempotent; frees CP state
                        except Exception:  # noqa: BLE001
                            pass
                state.replicas = keep
                state.version += 1
                self._notify_change()
            # a draining node that died (deadline hit, or crashed mid-drain)
            # takes its still-routable replicas with it
            left = [r for r in state.draining
                    if self._replica_key(r) not in on_dead_nodes]
            if len(left) != len(state.draining):
                for r in state.draining:
                    if self._replica_key(r) in on_dead_nodes:
                        state.health_fails.pop(self._replica_key(r), None)
                        try:
                            ray_tpu.kill(r)
                        except Exception:  # noqa: BLE001
                            pass
                state.draining = left
                state.version += 1
                self._notify_change()
            # a STARTING replica on a dead node will never become ready;
            # a WARMING one will never finish its warm_start — both are
            # pre-table, so no version bump, just re-place via scale-up
            still = [r for r in state.starting
                     if self._replica_key(r) not in on_dead_nodes]
            if len(still) != len(state.starting):
                for r in state.starting:
                    if self._replica_key(r) in on_dead_nodes:
                        try:
                            ray_tpu.kill(r)
                        except Exception:  # noqa: BLE001
                            pass
                state.starting = still
            warm_left = [r for r in state.warming
                         if self._replica_key(r) not in on_dead_nodes]
            if len(warm_left) != len(state.warming):
                for r in state.warming:
                    if self._replica_key(r) in on_dead_nodes:
                        t = state._warm_tasks.pop(self._replica_key(r), None)
                        if t is not None:
                            t.cancel()
                        try:
                            ray_tpu.kill(r)
                        except Exception:  # noqa: BLE001
                            pass
                state.warming = warm_left

    async def _move_replicas_on_draining_nodes(self):
        """Drain node-DRAINING events: replicas on those nodes move
        replicas → draining. They stay in the routing table (the node keeps
        serving until its drain deadline) but stop counting toward target,
        so the scale-up pass pre-starts replacements elsewhere this same
        tick — the table is only flipped away from them once the
        replacements are ready (ref: DrainRaylet + deployment_state
        graceful replacement)."""
        with self._dead_nodes_lock:
            draining, self._draining_nodes = list(self._draining_nodes), []
        if not draining:
            return
        draining_set = set(draining)

        def _list_actors_blocking():
            from ray_tpu.util import state as state_api
            return state_api.list_actors(limit=100000)

        try:
            actors = await asyncio.get_event_loop().run_in_executor(
                None, _list_actors_blocking)
        except Exception:  # noqa: BLE001 — CP briefly away; retry next event
            logger.exception("list_actors failed while handling node drain")
            with self._dead_nodes_lock:
                self._draining_nodes.extend(draining)
            return
        on_draining = {a["actor_id"] for a in actors
                       if a.get("node_id") in draining_set}
        for state in self._deployments.values():
            moving = [r for r in state.replicas
                      if self._replica_key(r) in on_draining]
            if moving:
                logger.warning(
                    "%s: %d replica(s) on draining node(s) %s — pre-starting "
                    "replacements before retiring them",
                    state.full_name(), len(moving),
                    [n[:8] for n in draining_set])
                state.replicas = [r for r in state.replicas
                                  if self._replica_key(r) not in on_draining]
                state.draining.extend(moving)
                # no version bump: the routing table still contains them
                # Drain pre-move spill (ISSUE 14): tell the moving
                # replicas to push in-flight KV chains into the tier NOW
                # — when the node dies, streams mid-generation there get
                # re-dispatched as continuations and the replacements
                # restore this work instead of recomputing it.
                # Fire-and-forget: drain must not block on a spill.
                for r in moving:
                    try:
                        r.prepare_to_move.remote()  # graftlint: fire-and-forget
                    except Exception:  # noqa: BLE001
                        pass
            # STARTING/WARMING replicas on a draining node would come up
            # on a node about to disappear — kill now, scale-up re-places
            # them (both are pre-table: no version traffic)
            doomed = [r for r in state.starting + state.warming
                      if self._replica_key(r) in on_draining]
            if doomed:
                state.starting = [r for r in state.starting
                                  if self._replica_key(r) not in on_draining]
                state.warming = [r for r in state.warming
                                 if self._replica_key(r) not in on_draining]
                for r in doomed:
                    t = state._warm_tasks.pop(self._replica_key(r), None)
                    if t is not None:
                        t.cancel()
                    try:
                        ray_tpu.kill(r)
                    except Exception:  # noqa: BLE001
                        pass

    async def _collect_summaries(self):
        """Refresh per-replica prefix summaries (ISSUE 10). Rate-limited;
        per-replica `since` versions make an idle fleet answer with tiny
        "unchanged" markers. A changed deployment bumps its summary_gen and
        wakes long-pollers WITHOUT a routing-table version bump (routers
        must not reshuffle probe caches for a summary delta)."""
        now = time.monotonic()
        if now - self._summary_ts < self._summary_interval_s:
            return
        self._summary_ts = now

        async def probe_summary(state, replica):
            key = self._replica_key(replica)
            if key in state.summary_unsupported:
                return False
            since = state.summary_versions.get(key)
            try:
                res = await asyncio.wait_for(_as_future(
                    replica.handle_request.remote(
                        "prefix_summary", (since,), {}), timeout=2.0), 3.0)
            except asyncio.TimeoutError:
                return False  # busy replica: retry next round
            except Exception as e:  # noqa: BLE001
                # only a proven-missing prefix_summary method (plain
                # deployment: getattr raises AttributeError, a wrong
                # signature TypeError) is permanent; any other failure
                # is a replica fault — transient blips must not exile a
                # healthy replica from affinity until replacement
                cause = e.cause if isinstance(e, TaskError) else e
                if isinstance(cause, (AttributeError, TypeError)):
                    state.summary_unsupported.add(key)
                return False
            if not isinstance(res, dict) or not res.get("supported"):
                state.summary_unsupported.add(key)
                return False
            if res.get("meta"):
                state.summary_meta = dict(res["meta"])
            if res.get("unchanged"):
                return False
            state.summary_versions[key] = int(res.get("version", 0))
            digests = list(res.get("digests") or [])
            if state.summaries.get(key) == digests:
                return False
            state.summaries[key] = digests
            return True

        for state in list(self._deployments.values()):
            changed = False
            # prune entries for replicas that left the routable-and-counted
            # set (dead, draining, replaced): they must exit every router's
            # affinity candidate set on the NEXT poll, and a replacement
            # replica starts cold
            live = {self._replica_key(r) for r in state.replicas}
            for k in [k for k in state.summaries if k not in live]:
                del state.summaries[k]
                state.summary_versions.pop(k, None)
                changed = True
            state.summary_unsupported &= live
            for k in [k for k in state.summary_versions if k not in live]:
                del state.summary_versions[k]
            if state.replicas:
                flags = await asyncio.gather(
                    *(probe_summary(state, r) for r in state.replicas))
                changed = changed or any(flags)
            if changed:
                state.summary_gen += 1
                self._notify_change()

    async def _warm_one(self, state: _DeploymentState, replica) -> dict:
        """Cache-warm one READY but unpublished replica (ISSUE 17): a
        single bounded warm_start RPC through the generic dispatch. The
        replica restores the fleet's hottest KV-tier chains into its
        prefix cache; unsupported deployments (plain callables, tier
        off) resolve immediately. A hung warm is promoted cold by the
        timeout rather than parked forever."""
        try:
            res = await asyncio.wait_for(_as_future(
                replica.handle_request.remote("warm_start", (), {}),
                timeout=30.0), 35.0)
        except Exception as e:  # noqa: BLE001 — promote cold
            cause = e.cause if isinstance(e, TaskError) else e
            if not isinstance(cause, (AttributeError, TypeError)):
                logger.warning("%s: warm_start failed — promoting cold: %r",
                               state.full_name(), e)
            return {"supported": False, "pages": 0}
        if isinstance(res, dict) and res.get("supported"):
            logger.info(
                "%s: warm start landed %s pages / %s chains in %s ms",
                state.full_name(), res.get("pages", 0),
                res.get("chains", 0), res.get("ms", 0.0))
            return res
        return {"supported": False, "pages": 0}

    async def _collect_scale_signals(self, state: _DeploymentState) -> dict:
        """Serve-plane signals for decide_signals (ISSUE 17), refreshed
        at most every 2 s and cached between refreshes. Everything
        degrades to absence (pure queue-length policy) when the exemplar
        store or affinity summaries aren't there."""
        now = time.monotonic()
        if now - state._signals_ts < 2.0:
            return state._signals
        state._signals_ts = now
        sig: dict = {}
        # affinity heat from the ISSUE-10 summaries already in hand:
        # per-replica resident-page skew (what the router's load ×
        # locality score is fighting) and the share of replicas holding
        # anything (what a downscale would evict)
        counts = [len(state.summaries.get(self._replica_key(r)) or [])
                  for r in state.replicas]
        if counts:
            mean = sum(counts) / len(counts)
            sig["prefill_skew"] = (round(max(counts) / mean, 3)
                                   if mean > 0 else 0.0)
            sig["affinity_hit_share"] = round(
                sum(1 for c in counts if c > 0) / len(counts), 3)

        # PR 12 attribution: violation count + dominant p99-TTFT stage
        # for this deployment's exemplar window (CP call → executor)
        def _report():
            from ray_tpu.util import state as state_api
            return state_api.slo_report(deployment=state.name)

        try:
            rep = await asyncio.get_event_loop().run_in_executor(
                None, _report)
        except Exception:  # noqa: BLE001 — attribution absent
            rep = None
        if isinstance(rep, dict) and rep.get("count"):
            sig["slo_violations"] = int(rep.get("violations") or 0)
            dom = rep.get("dominant_stage") or {}
            if isinstance(dom, dict) and dom:
                sig["dominant_stage"] = max(dom.items(),
                                            key=lambda kv: kv[1])[0]
        state._signals = sig
        return sig

    def _record_scale(self, state: _DeploymentState, prev: int, new: int,
                      reason: str, signals: Optional[dict] = None):
        """Append to the deployment's bounded scale-decision log (the
        dashboard/harness flight recorder) and bump the reason counter."""
        state.scale_counters[reason] = \
            state.scale_counters.get(reason, 0) + 1
        state.scale_decisions.append({
            "ts": time.time(), "from": int(prev), "to": int(new),
            "reason": reason, "signals": dict(signals or {})})
        del state.scale_decisions[:-50]
        # full history rides the journal — the CP's severity-tiered
        # store outlives the last-50 local window above (ISSUE 19)
        _fr.emit("replica_scale", "INFO",
                 deployment=state.full_name(), reason=reason,
                 attrs={"from": int(prev), "to": int(new),
                        "signals": dict(signals or {})})

    async def _pick_downscale_victim(self, state: _DeploymentState):
        """Coldest, least-loaded replica: fewest exported prefix-summary
        digests first (retiring a hot holder evicts the fleet's working
        set), then shortest live queue. An unreachable probe scores as
        idle — the health sweep reclaims a genuinely dead replica either
        way."""
        scored = []
        for i, r in enumerate(state.replicas):
            heat = len(state.summaries.get(self._replica_key(r)) or [])
            try:
                q = int(await asyncio.wait_for(
                    _as_future(r.get_queue_len.remote()), 2.0))
            except Exception:  # noqa: BLE001
                q = 0
            scored.append((heat, q, i, r))
        scored.sort(key=lambda t: (t[0], t[1], -t[2]))
        return scored[0][3]

    async def set_target_replicas(self, app_name: str,
                                  deployment: Optional[str] = None,
                                  target: Optional[int] = None,
                                  delta: Optional[int] = None,
                                  reason: str = "manual") -> dict:
        """Imperative scale knob (bench schedules, `replica_scale` chaos
        events, operators). Sets the reconcile target directly: scale-up
        goes through STARTING → WARMING → one atomic publish; scale-down
        drains the coldest replica with zero dropped requests. Clamped
        to the autoscaling [min, max] when one is configured, and to
        >= 1 always. Returns {full_name: target} for the touched
        deployments."""
        self._ensure_started()
        out = {}
        for state in list(self._deployments.values()):
            if state.app != app_name:
                continue
            if deployment is not None and state.name != deployment:
                continue
            new = state.target if target is None else int(target)
            if target is None and delta is not None:
                new = state.target + int(delta)
            asc = state.config.autoscaling_config
            if asc is not None:
                new = max(asc.min_replicas, min(asc.max_replicas, new))
            new = max(1, new)
            if new != state.target:
                self._record_scale(state, state.target, new, reason,
                                   state._signals)
                logger.info("set_target_replicas %s: %d -> %d (%s)",
                            state.full_name(), state.target, new, reason)
                state.target = new
                state._pending_target = None
            out[state.full_name()] = state.target
        return out

    async def _reconcile_once(self):
        await self._drop_replicas_on_dead_nodes()
        await self._move_replicas_on_draining_nodes()
        for state in list(self._deployments.values()):
            # readiness: a freshly created replica becomes routable only
            # after its first successful health check (the reference's
            # STARTING → RUNNING transition) — publishing it earlier would
            # queue live requests behind actor creation
            if state.starting:
                ready_flags = await asyncio.gather(
                    *(_probe_ready(r) for r in state.starting))
                became = [r for r, ok in zip(state.starting, ready_flags)
                          if ok]
                if became:
                    state.starting = [
                        r for r, ok in zip(state.starting, ready_flags)
                        if not ok]
                    # cache-warm scale-up (ISSUE 17): a ready replica is
                    # NOT published yet — it first pre-populates its
                    # prefix cache from the KV tier (WARMING). Promotion
                    # below is the only way into the routing table.
                    state.warming.extend(became)
                    for r in became:
                        state._warm_tasks[self._replica_key(r)] = \
                            asyncio.ensure_future(self._warm_one(state, r))

            # promote warmed replicas. The list mutation and the version
            # bump happen in ONE synchronous block (no await between), so
            # a long-poller can never observe a table that contains the
            # new replica under the old version — or the bumped version
            # without the replica (ISSUE 17 atomicity fix). Warming is
            # best-effort: a failed/unsupported/timed-out warm promotes
            # the replica cold rather than parking it forever.
            if state.warming:
                done = [r for r in state.warming
                        if state._warm_tasks.get(
                            self._replica_key(r), None) is None
                        or state._warm_tasks[self._replica_key(r)].done()]
                if done:
                    for r in done:
                        t = state._warm_tasks.pop(self._replica_key(r), None)
                        res = None
                        if t is not None and t.done() and not t.cancelled():
                            try:
                                res = t.result()
                            except Exception:  # noqa: BLE001
                                res = None
                        if isinstance(res, dict) and res.get("supported"):
                            state.warm_stats["replicas_warmed"] += 1
                            state.warm_stats["pages"] += int(
                                res.get("pages") or 0)
                            state.warm_stats["ms"] = round(
                                state.warm_stats["ms"]
                                + float(res.get("ms") or 0.0), 3)
                            _fr.emit(
                                "warm_start", "INFO",
                                deployment=state.full_name(),
                                replica=self._replica_key(r),
                                attrs={
                                    "pages": int(res.get("pages") or 0),
                                    "chains": int(res.get("chains") or 0),
                                    "ms": float(res.get("ms") or 0.0)})
                    done_set = {self._replica_key(r) for r in done}
                    state.warming = [
                        r for r in state.warming
                        if self._replica_key(r) not in done_set]
                    state.replicas.extend(done)
                    state.version += 1
                    self._notify_change()
                    _fr.emit("table_publish", "INFO",
                             deployment=state.full_name(),
                             reason="warmed replicas promoted",
                             attrs={"version": state.version,
                                    "replicas": len(state.replicas)})

            # health: drop replicas only after `health_check_failure_threshold`
            # CONSECUTIVE failures (one transient miss must not cost a
            # replica), and kill() the dropped actor so its worker process
            # doesn't leak
            threshold = max(1, state.config.health_check_failure_threshold)
            alive = []
            for r in state.replicas:
                key = self._replica_key(r)
                try:
                    await asyncio.wait_for(_as_future(
                        r.check_health.remote(),
                        timeout=state.config.health_check_timeout_s),
                        state.config.health_check_timeout_s + 1.0)
                    state.health_fails.pop(key, None)
                    alive.append(r)
                except Exception:  # noqa: BLE001
                    fails = state.health_fails.get(key, 0) + 1
                    state.health_fails[key] = fails
                    logger.warning(
                        "replica of %s failed health check (%d/%d)",
                        state.full_name(), fails, threshold)
                    if fails < threshold:
                        alive.append(r)
                        continue
                    state.health_fails.pop(key, None)
                    _fr.emit("replica_death", "ERROR",
                             deployment=state.full_name(), replica=key,
                             reason=f"{fails} consecutive failed "
                                    "health checks")
                    try:
                        ray_tpu.kill(r)
                    except Exception:  # noqa: BLE001
                        pass
            if len(alive) != len(state.replicas):
                state.replicas = alive
                state.version += 1
                self._notify_change()
                _fr.emit("table_publish", "INFO",
                         deployment=state.full_name(),
                         reason="dead replicas removed",
                         attrs={"version": state.version,
                                "replicas": len(state.replicas)})

            # draining replicas are still routable, so they get the same
            # health policy — one that dies mid-drain must leave the table
            if state.draining:
                keep_draining = []
                for r in state.draining:
                    key = self._replica_key(r)
                    try:
                        await asyncio.wait_for(_as_future(
                            r.check_health.remote(),
                            timeout=state.config.health_check_timeout_s),
                            state.config.health_check_timeout_s + 1.0)
                        state.health_fails.pop(key, None)
                        keep_draining.append(r)
                    except Exception:  # noqa: BLE001
                        fails = state.health_fails.get(key, 0) + 1
                        state.health_fails[key] = fails
                        if fails < threshold:
                            keep_draining.append(r)
                            continue
                        state.health_fails.pop(key, None)
                        try:
                            ray_tpu.kill(r)
                        except Exception:  # noqa: BLE001
                            pass
                if len(keep_draining) != len(state.draining):
                    state.draining = keep_draining
                    state.version += 1
                    self._notify_change()

            # retire draining replicas once enough replacements are READY:
            # flip the routing table first (version bump → routers/proxies
            # long-poll the new set), THEN stop the old replicas gracefully
            # so their in-flight requests complete — a drain drops zero
            # requests (ISSUE acceptance)
            if state.draining and len(state.replicas) >= state.target:
                retired, state.draining = list(state.draining), []
                state.version += 1
                self._notify_change()
                logger.info("%s: retiring %d drained replica(s) — "
                            "replacements are serving", state.full_name(),
                            len(retired))
                for r in retired:
                    state.health_fails.pop(self._replica_key(r), None)
                    try:
                        await asyncio.wait_for(_as_future(
                            r.prepare_for_shutdown.remote(
                                state.config.graceful_shutdown_timeout_s)),
                            state.config.graceful_shutdown_timeout_s + 5.0)
                    except Exception:  # noqa: BLE001
                        pass
                    try:
                        ray_tpu.kill(r)
                    except Exception:  # noqa: BLE001
                        pass

            # autoscaling: queue-length policy folded with serve-plane
            # signals (ISSUE 17) — PR 12 SLO attribution (violations +
            # dominant p99-TTFT stage) and PR 10/14 affinity heat (hit
            # share, per-replica summary-page skew). Signals degrade to
            # {} when the exemplar store or summaries are absent, which
            # reduces decide_signals to the original queue policy.
            asc = state.config.autoscaling_config
            if asc is not None and state.replicas:
                total = 0
                for r in state.replicas:
                    try:
                        total += await asyncio.wait_for(
                            _as_future(r.get_queue_len.remote()), 2.0)
                    except Exception:  # noqa: BLE001
                        pass
                signals = await self._collect_scale_signals(state)
                desired, reason = asc.decide_signals(
                    len(state.replicas), total, signals)
                now = time.monotonic()
                if desired != state.target:
                    delay = (asc.upscale_delay_s if desired > state.target
                             else asc.downscale_delay_s)
                    if state._pending_target != desired:
                        state._pending_target = desired
                        state._scale_pending_since = now
                    elif now - state._scale_pending_since >= delay:
                        logger.info("autoscaling %s: %d -> %d (%s)",
                                    state.full_name(), state.target,
                                    desired, reason)
                        self._record_scale(state, state.target, desired,
                                           reason, signals)
                        state.target = desired
                        state._pending_target = None
                else:
                    state._pending_target = None
                    # a heat-guard refusal is a scale decision too: log
                    # it once per continuous guard episode, not per tick
                    if reason == "heat_guard":
                        if not state._guard_episode:
                            state._guard_episode = True
                            self._record_scale(state, state.target,
                                               state.target, reason,
                                               signals)
                    else:
                        state._guard_episode = False

            # scale toward target; new replicas go through STARTING (and
            # then WARMING) and are published to routers only once warm
            counted = (len(state.replicas) + len(state.starting)
                       + len(state.warming))
            while counted < state.target:
                replica = ServeReplica.options(
                    max_concurrency=max(100, state.config.max_ongoing_requests),
                    **state.config.ray_actor_options).remote(
                    state.name, state.serialized_cls, state.init_args,
                    state.init_kwargs, state.config.user_config,
                    state.config.max_ongoing_requests)
                state.starting.append(replica)
                counted += 1
            while counted > state.target:
                counted -= 1
                # prefer killing replicas that never took traffic
                if state.starting:
                    victim = state.starting.pop()
                elif state.warming:
                    victim = state.warming.pop()
                    t = state._warm_tasks.pop(
                        self._replica_key(victim), None)
                    if t is not None:
                        t.cancel()
                else:
                    # graceful downscale (ISSUE 17): pick the coldest,
                    # least-loaded replica and move it to DRAINING — the
                    # retirement block above flips the routing table
                    # first next tick, then prepare_for_shutdown lets
                    # its in-flight streams finish (spilling KV for any
                    # that must resume elsewhere) before the kill. No
                    # request is dropped, no resumed stream diverges.
                    victim = await self._pick_downscale_victim(state)
                    state.replicas.remove(victim)
                    state.draining.append(victim)
                    logger.info(
                        "%s: downscale — draining replica %s",
                        state.full_name(),
                        self._replica_key(victim)[:8])
                    continue  # still routable; retired gracefully later
                try:
                    ray_tpu.kill(victim)
                except Exception:  # noqa: BLE001
                    pass

        # prefix-affinity summaries ride the reconcile loop (rate-limited
        # inside): collection must see the post-churn replica sets so a
        # replica dropped above leaves every router's candidate set now
        await self._collect_summaries()


async def _as_future(ref, timeout: Optional[float] = None):
    """Adapt a ray_tpu ObjectRef get to asyncio without blocking the loop.
    Pass `timeout` so the executor thread unblocks itself even when the
    awaiting coroutine gives up first (asyncio.wait_for cannot interrupt
    a thread already parked in ray_tpu.get)."""
    loop = asyncio.get_event_loop()
    return await loop.run_in_executor(
        None, lambda: ray_tpu.get(ref, timeout=timeout))


async def _probe_ready(replica) -> bool:
    """One bounded readiness probe (first health check) of a STARTING
    replica. The short timeout keeps the reconcile tick fast; a replica
    still constructing simply stays in STARTING until a later tick."""
    try:
        await asyncio.wait_for(
            _as_future(replica.check_health.remote(), timeout=1.0), 2.0)
        return True
    except Exception:  # noqa: BLE001 — not up yet (or already dead)
        return False


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME, timeout=0.2)
    except Exception:  # noqa: BLE001 - create it
        return ServeController.options(
            name=CONTROLLER_NAME, lifetime="detached",
            max_concurrency=1000).remote()
