"""Compiled-pipeline (aDAG analog) + cross-node channel tests
(reference: python/ray/dag/tests/experimental/test_accelerated_dag.py
model — compile once, execute many, teardown; cross-node mutable pushes
per node_manager.proto RegisterMutableObject/PushMutableObject)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.channel import Channel
from ray_tpu.core.cluster import Cluster
from ray_tpu.dag import CompiledPipeline


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module


@ray_tpu.remote
class Plus:
    def __init__(self, n):
        self.n = n
        self.calls = 0

    def apply(self, x):
        self.calls += 1
        return x + self.n

    def ncalls(self):
        return self.calls


def test_rtpu_call_generic_entry(ray_start_regular):
    """__rtpu_call__ runs an arbitrary callable against the actor instance
    (the reference's actor.__ray_call__)."""
    a = Plus.options(max_concurrency=2).remote(5)
    out = ray_tpu.get(
        a.__rtpu_call__.remote(lambda inst, k: inst.n * k, 3), timeout=60)
    assert out == 15


def test_compiled_pipeline_two_stages(ray_start_regular):
    a = Plus.options(max_concurrency=2).remote(1)
    b = Plus.options(max_concurrency=2).remote(10)
    pipe = CompiledPipeline([(a, "apply"), (b, "apply")]).compile()
    try:
        refs = [pipe.execute(i) for i in range(3)]  # up to stages+1 in flight
        assert [r.get(timeout=60) for r in refs] == [i + 11 for i in range(3)]
        for i in range(3, 5):
            assert pipe.execute(i).get(timeout=60) == i + 11
        # out-of-order gets still deliver the right values
        r1 = pipe.execute(100)
        r2 = pipe.execute(200)
        assert r2.get(timeout=60) == 211
        assert r1.get(timeout=60) == 111
        # over-submission raises instead of deadlocking (reference:
        # CompiledDAG max_buffered_results)
        import pytest as _pytest
        held = [pipe.execute(i) for i in range(3)]
        with _pytest.raises(RuntimeError, match="in flight"):
            pipe.execute(99)
        assert [r.get(timeout=60) for r in held] == [11, 12, 13]
    finally:
        pipe.close()
    # loop tasks exited and reported their processed counts; the actors
    # are free again for plain calls
    assert ray_tpu.get(a.ncalls.remote(), timeout=60) == 10


def test_compiled_pipeline_cross_node():
    """Stages on DIFFERENT nodes: the inter-stage edge crosses nodes via
    the agent channel relay."""
    ray_tpu.shutdown()
    cluster = Cluster()
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.core.task_spec import NodeAffinityStrategy

        a = Plus.options(
            max_concurrency=2,
            scheduling_strategy=NodeAffinityStrategy(
                node_id_hex=n1.node_id.hex())).remote(1)
        b = Plus.options(
            max_concurrency=2,
            scheduling_strategy=NodeAffinityStrategy(
                node_id_hex=n2.node_id.hex())).remote(10)
        pipe = CompiledPipeline([(a, "apply"), (b, "apply")]).compile()
        try:
            for i in range(8):
                assert pipe.execute(i).get(timeout=120) == i + 11
        finally:
            pipe.close()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_cross_node_channel_relay():
    """A driver-side channel read by an actor on ANOTHER node: values flow
    through the shadow-channel relay with backpressure and close cascades."""
    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.core.task_spec import NodeAffinityStrategy

        ch = Channel(capacity=1 << 16, num_readers=1)
        reader = ch.remote_reader(0)

        @ray_tpu.remote(scheduling_strategy=NodeAffinityStrategy(
            node_id_hex=n2.node_id.hex()))
        class Sink:
            def drain(self, reader, n):
                from ray_tpu.core.channel import ChannelClosedError
                got = []
                try:
                    for _ in range(n):
                        got.append(reader.read(timeout=30.0))
                except ChannelClosedError:
                    pass
                return got

        s = Sink.remote()
        # ask for MORE than will be written: the drain must receive every
        # value, then see the writer's close cascade through the relay
        # (ChannelClosedError) instead of timing out
        fut = s.drain.remote(reader, 12)
        for i in range(10):
            ch.write(i, timeout=30.0)
        time.sleep(0.3)  # let the relay deliver the tail before closing
        ch.close()
        assert ray_tpu.get(fut, timeout=120) == list(range(10))
        ch.unlink()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
