"""Model multiplexing: many models per deployment, LRU-cached per replica.

TPU-native analog of the reference's multiplexing
(/root/reference/python/ray/serve/multiplex.py — @serve.multiplexed model
loader + serve.get_multiplexed_model_id(); the router prefers replicas that
already hold the requested model). Affinity here is rendezvous hashing on
the model id — deterministic with zero telemetry: the same model id lands
on the same replica while the replica set is stable, so its cache stays
hot (LoRA adapters etc.), and reshuffles minimally when replicas change.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
from collections import OrderedDict
from typing import Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rtpu_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a request handler: the model id this request was routed for."""
    return _current_model_id.get()


def _set_multiplexed_model_id(model_id: str):
    _current_model_id.set(model_id)


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for an async model-loader method: results are LRU-cached
    per replica, keyed by model id; the oldest model is evicted (and its
    __del__ releases device memory) beyond the cap."""

    def decorate(fn):
        cache: OrderedDict[str, object] = OrderedDict()
        pending: dict[str, asyncio.Future] = {}  # in-flight load dedup
        lock = asyncio.Lock()

        @functools.wraps(fn)
        async def wrapper(self_or_id, *args):
            # support both method (self, model_id) and free fn (model_id)
            model_id = args[0] if args else self_or_id
            while True:
                async with lock:
                    if model_id in cache:
                        cache.move_to_end(model_id)
                        return cache[model_id]
                    fut = pending.get(model_id)
                    if fut is None:
                        # we are the loader; others await our future (a
                        # duplicate load of an LLM is 2x device memory)
                        fut = pending[model_id] = \
                            asyncio.get_running_loop().create_future()
                        break
                try:
                    return await asyncio.shield(fut)
                except Exception:
                    continue  # loader failed: retry (maybe become loader)
            try:
                out = fn(self_or_id, *args) if args else fn(self_or_id)
                if asyncio.iscoroutine(out):
                    out = await out
            except BaseException as e:
                async with lock:
                    pending.pop(model_id, None)
                if not fut.done():
                    fut.set_exception(e)
                    fut.exception()  # consumed; avoid un-retrieved warnings
                raise
            async with lock:
                cache[model_id] = out
                cache.move_to_end(model_id)
                pending.pop(model_id, None)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
            if not fut.done():
                fut.set_result(out)
            return out

        wrapper._is_multiplexed = True
        return wrapper

    if func is not None:
        return decorate(func)
    return decorate


def rendezvous_pick(replicas: list, model_id: str):
    """Highest-random-weight hashing: stable replica choice per model id.

    Weights hash the replica's stable identity (actor id), not its list
    index — index-keyed weights would reshuffle nearly every model's
    assignment whenever the replica set changes, mass-evicting warm
    caches on each scale event."""
    import hashlib

    def weight(idx: int) -> int:
        rep = replicas[idx]
        rid = getattr(rep, "actor_id", None)
        key = rid.hex() if rid is not None else str(idx)
        return int.from_bytes(hashlib.sha1(
            f"{model_id}:{key}".encode()).digest()[:8], "big")

    return max(range(len(replicas)), key=weight)
