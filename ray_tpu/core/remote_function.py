"""@remote functions.

TPU-native analog of the reference's RemoteFunction
(/root/reference/python/ray/remote_function.py:41, _remote at :314).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from ray_tpu.core.task_spec import (
    DefaultStrategy,
    NodeAffinityStrategy,
    NodeLabelStrategy,
    PlacementGroupStrategy,
    SpreadStrategy,
)

_DEFAULT_RESOURCES = {"CPU": 1.0}


def _build_strategy(options: dict):
    strategy = options.get("scheduling_strategy")
    if strategy is None:
        return DefaultStrategy()
    if isinstance(strategy, str):
        if strategy == "SPREAD":
            return SpreadStrategy()
        if strategy == "DEFAULT":
            return DefaultStrategy()
        raise ValueError(f"unknown scheduling strategy {strategy!r}")
    if isinstance(strategy, (DefaultStrategy, SpreadStrategy, NodeAffinityStrategy,
                             NodeLabelStrategy, PlacementGroupStrategy)):
        return strategy
    # placement group objects
    from ray_tpu.core.placement_group import PlacementGroup
    if isinstance(strategy, PlacementGroup):
        return PlacementGroupStrategy(pg_id=strategy.id, bundle_index=-1)
    raise TypeError(f"bad scheduling_strategy: {strategy!r}")


def _build_resources(options: dict) -> dict[str, float]:
    resources = dict(options.get("resources") or {})
    if "num_cpus" in options and options["num_cpus"] is not None:
        resources["CPU"] = float(options["num_cpus"])
    if "num_tpus" in options and options["num_tpus"] is not None:
        resources["TPU"] = float(options["num_tpus"])
    if "num_gpus" in options and options["num_gpus"] is not None:
        resources["GPU"] = float(options["num_gpus"])
    if "memory" in options and options["memory"] is not None:
        resources["memory"] = float(options["memory"])
    if "CPU" not in resources:
        resources["CPU"] = 1.0
    return resources


class RemoteFunction:
    def __init__(self, fn: Callable, **options):
        self._fn = fn
        self._options = options
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._options, **options}
        return RemoteFunction(self._fn, **merged)

    def _remote(self, args, kwargs, options) -> Any:
        from ray_tpu.core import api
        rt = api._get_runtime()
        num_returns = options.get("num_returns", 1)
        refs = rt.submit_task(
            self._fn, args, kwargs,
            num_returns=num_returns,
            resources=_build_resources(options),
            strategy=_build_strategy(options),
            max_retries=options.get("max_retries"),
            retry_exceptions=bool(options.get("retry_exceptions", False)),
            name=options.get("name", "") or self._fn.__name__,
            runtime_env=options.get("runtime_env"))
        if num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._fn.__name__}' cannot be called directly; "
            f"use '{self._fn.__name__}.remote()'.")
