"""Multi-agent environments, runners, and per-policy learning.

TPU-native analog of the reference's multi-agent stack
(rllib/env/multi_agent_env.py + multi_agent_env_runner.py + the
policies_to_train / policy_mapping_fn machinery): a MultiAgentEnv steps a
DICT of agent actions and returns per-agent observations/rewards; the
MultiAgentEnvRunner collects per-POLICY sample batches (agents sharing a
policy pool their transitions); MultiAgentPPO owns one module + one
optimizer per policy and runs the jitted PPO update per policy per
iteration.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.models import RLModule


class MultiAgentEnv:
    """Minimal multi-agent env protocol (reference MultiAgentEnv):
    reset/step speak dicts keyed by agent id."""

    agent_ids: list[str]
    observation_dim: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: dict[str, int]) -> tuple[
            dict[str, np.ndarray], dict[str, float], bool, bool]:
        """Returns (obs, rewards, terminated, truncated) — termination is
        environment-global (the __all__ convention collapsed)."""
        raise NotImplementedError


class MatchingGame(MultiAgentEnv):
    """Two-agent coordination game (test env): each agent sees a shared
    random context bit and earns +1 when BOTH pick the action equal to the
    bit, else 0. Optimal play is fully learnable from per-agent policies;
    random play earns 0.25/step each."""

    agent_ids = ["a0", "a1"]
    observation_dim = 2
    num_actions = 2

    def __init__(self, episode_len: int = 16):
        self._len = episode_len
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._bit = 0

    def _obs(self) -> dict[str, np.ndarray]:
        one_hot = np.zeros(2, np.float32)
        one_hot[self._bit] = 1.0
        return {a: one_hot.copy() for a in self.agent_ids}

    def reset(self, seed: Optional[int] = None) -> dict[str, np.ndarray]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._bit = int(self._rng.integers(2))
        return self._obs()

    def step(self, actions: dict[str, int]):
        both_right = all(actions[a] == self._bit for a in self.agent_ids)
        rewards = {a: (1.0 if both_right else 0.0) for a in self.agent_ids}
        self._t += 1
        self._bit = int(self._rng.integers(2))
        truncated = self._t >= self._len
        return self._obs(), rewards, False, truncated


@ray_tpu.remote
class MultiAgentEnvRunner:
    """Rollout actor for multi-agent envs (reference
    multi_agent_env_runner.py): steps every agent each tick, routes each
    agent's transition into its POLICY's batch via policy_mapping_fn."""

    def __init__(self, env_creator, policy_ids: list[str],
                 policy_mapping: Callable[[str], str], module: RLModule,
                 seed: int = 0):
        import jax

        self._env = env_creator()
        self._policy_ids = list(policy_ids)
        self._map = policy_mapping
        self._rng = np.random.default_rng(seed)
        self._obs = self._env.reset(seed=seed)
        self._logits_fn = jax.jit(module.forward_inference)
        self._value_fn = jax.jit(lambda p, o: module.forward_train(p, o)[1])
        self._ep_return = 0.0
        self._done_returns: list[float] = []

    def sample(self, params_per_policy: dict, num_steps: int) -> dict:
        """Collect num_steps env ticks; returns {policy_id: column_batch}.

        Batches are AGENT-MAJOR: each agent mapped to the policy contributes
        its own time-ordered trajectory of num_steps rows, concatenated
        (agent0's rows, then agent1's, ...). Episode boundaries (termination
        OR truncation/reset) are marked in ``dones`` so GAE never bootstraps
        across a reset, and ``last_obs`` carries one bootstrap observation
        PER AGENT ([n_agents, obs_dim])."""
        env = self._env
        # per (pid, agent) trajectory columns — pooled agents stay separate
        traj: dict[str, dict[str, dict[str, list]]] = {
            pid: {a: {"obs": [], "actions": [], "rewards": [], "dones": [],
                      "logp": []}
                  for a in env.agent_ids if self._map(a) == pid}
            for pid in self._policy_ids}
        for _ in range(num_steps):
            actions: dict[str, int] = {}
            staged = []
            for agent in env.agent_ids:
                pid = self._map(agent)
                params = params_per_policy[pid]
                ob = self._obs[agent]
                logits = np.asarray(self._logits_fn(params, ob[None]))[0]
                z = logits - logits.max()
                p = np.exp(z) / np.exp(z).sum()
                a = int(self._rng.choice(len(p), p=p))
                actions[agent] = a
                staged.append((pid, agent, ob, a,
                               float(z[a] - np.log(np.exp(z).sum()))))
            obs2, rewards, term, trunc = env.step(actions)
            self._ep_return += sum(rewards.values())
            done = term or trunc
            for pid, agent, ob, a, logp in staged:
                c = traj[pid][agent]
                c["obs"].append(ob)
                c["actions"].append(a)
                c["rewards"].append(rewards[agent])
                c["dones"].append(float(done))
                c["logp"].append(logp)
            if done:
                self._done_returns.append(self._ep_return)
                self._ep_return = 0.0
                obs2 = env.reset()
            self._obs = obs2
        out = {}
        for pid, agents in traj.items():
            ids = sorted(agents)
            obs = np.concatenate(
                [np.asarray(agents[a]["obs"], np.float32) for a in ids]) \
                if ids else np.zeros((0, env.observation_dim), np.float32)
            cat = lambda k, dt: np.concatenate(  # noqa: E731
                [np.asarray(agents[a][k], dt) for a in ids]) if ids else \
                np.zeros((0,), dt)
            out[pid] = {
                "obs": obs,
                "actions": cat("actions", np.int32),
                "rewards": cat("rewards", np.float32),
                "dones": cat("dones", np.float32),
                "logp": cat("logp", np.float32),
                "vf": np.asarray(self._value_fn(
                    params_per_policy[pid], obs)) if len(obs) else
                np.zeros((0,), np.float32),
                "last_obs": np.stack([self._obs[a] for a in ids]) if ids
                else np.zeros((0, env.observation_dim), np.float32),
            }
        return out

    def episode_stats(self) -> dict:
        rets, self._done_returns = self._done_returns, []
        return {"episode_returns": rets}


class MultiAgentPPO:
    """Per-policy PPO over a multi-agent env (the reference's
    policies={...} + policy_mapping_fn shape): one RLModule + optimizer +
    jitted update per policy; each iteration samples once and updates
    every policy on its own pooled batch."""

    def __init__(self, env_creator, *, policies: list[str],
                 policy_mapping: Callable[[str], str],
                 num_env_runners: int = 2, rollout_steps: int = 64,
                 lr: float = 3e-3, gamma: float = 0.95,
                 hidden: tuple = (32, 32), seed: int = 0):
        import jax
        import optax

        from ray_tpu.rllib.ppo import _gae

        probe = env_creator()
        self.module = RLModule(probe.observation_dim, probe.num_actions,
                               hidden=hidden)
        self.policies = list(policies)
        self.params = {
            pid: self.module.init(jax.random.PRNGKey(seed + i))
            for i, pid in enumerate(self.policies)}
        self._opt = optax.adam(lr)
        self._opt_state = {pid: self._opt.init(p)
                           for pid, p in self.params.items()}
        self._rollout_steps = rollout_steps
        self._runners = [
            MultiAgentEnvRunner.remote(env_creator, self.policies,
                                       policy_mapping, self.module,
                                       seed=seed + i)
            for i in range(num_env_runners)]
        self._iter = 0

        module = self.module
        clip, vf_c, ent_c, lam = 0.2, 0.5, 0.01, 0.95

        def loss_fn(params, batch):
            logits, values = module.forward_train(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jax.numpy.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1)[:, 0]
            # batch is agent-major ([n_agents * T] rows): GAE runs per agent
            # trajectory (vmapped over the agent axis), never across agents,
            # bootstrapping each from its own last_obs value
            n_agents = batch["last_obs"].shape[0]
            _, last_v = module.forward_train(params, batch["last_obs"])
            per = lambda x: x.reshape(n_agents, -1)  # noqa: E731
            adv, targets = jax.vmap(
                _gae, in_axes=(0, 0, 0, 0, None, None))(
                per(batch["rewards"]), per(batch["dones"]),
                per(batch["vf"]), last_v, gamma, lam)
            adv, targets = adv.reshape(-1), targets.reshape(-1)
            adv = jax.lax.stop_gradient(
                (adv - adv.mean()) / (adv.std() + 1e-8))
            ratio = jax.numpy.exp(logp - batch["logp"])
            surrogate = jax.numpy.minimum(
                ratio * adv,
                jax.numpy.clip(ratio, 1 - clip, 1 + clip) * adv)
            pg_loss = -surrogate.mean()
            vf_loss = ((values - jax.lax.stop_gradient(targets)) ** 2).mean()
            entropy = -(jax.numpy.exp(logp_all) * logp_all).sum(-1).mean()
            return pg_loss + vf_c * vf_loss - ent_c * entropy

        @jax.jit
        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = update

    def train(self) -> dict:
        t0 = time.monotonic()
        params_ref = ray_tpu.put(self.params)
        samples = ray_tpu.get(
            [r.sample.remote(params_ref, self._rollout_steps)
             for r in self._runners], timeout=300.0)
        losses = {}
        for pid in self.policies:
            for s in samples:
                batch = s[pid]
                if not len(batch["obs"]):
                    continue
                self.params[pid], self._opt_state[pid], loss = self._update(
                    self.params[pid], self._opt_state[pid], batch)
                losses[pid] = float(loss)
        self._iter += 1
        stats = ray_tpu.get([r.episode_stats.remote() for r in self._runners],
                            timeout=60.0)
        rets = [x for s in stats for x in s["episode_returns"]]
        return {"training_iteration": self._iter,
                "episode_return_mean": float(np.mean(rets)) if rets else None,
                "policy_loss": losses, "time_this_iter_s":
                time.monotonic() - t0}

    def mean_step_reward(self, num_steps: int = 64) -> float:
        """Average per-(tick, agent) reward under the CURRENT (stochastic)
        policies — the learning-progress metric for cooperative envs."""
        env_stats = ray_tpu.get(
            [r.sample.remote(ray_tpu.put(self.params), num_steps)
             for r in self._runners[:1]], timeout=300.0)[0]
        total = sum(float(b["rewards"].sum()) for b in env_stats.values())
        rows = sum(len(b["rewards"]) for b in env_stats.values())
        return total / max(rows, 1)

    def stop(self) -> None:
        for r in self._runners:
            ray_tpu.kill(r)
