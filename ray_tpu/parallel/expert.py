"""Expert parallelism: MoE dispatch/combine with all-to-all over the expert axis.

The reference has no in-tree MoE execution — the BASELINE "Mixtral 8×7B MoE
expert-parallel across Ray actors" config must be built natively (SURVEY.md
§2.3 row EP). Design: experts are sharded over the mesh "expert" axis; tokens
are routed top-k with capacity buckets (Switch/GShard style: static shapes, so
XLA tiles the expert matmuls on the MXU), and `lax.all_to_all` moves token
buckets token-shard↔expert-shard over ICI.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import shard_map_compat as shard_map
from jax.sharding import Mesh, PartitionSpec as P


def top_k_gating(gate_logits, k: int):
    """Top-k gate probs/indices, renormalized over the chosen experts.
    gate_logits: [T, E] → (probs [T,k], idx [T,k])."""
    probs = jax.nn.softmax(gate_logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_p, top_i


def _dispatch_masks(top_i, top_p, num_experts: int, capacity: int):
    """Build combine/dispatch tensors [T, E, C] from top-k choices
    (GShard-style position-in-expert bucketing; overflow tokens drop)."""
    t, k = top_i.shape
    combine = jnp.zeros((t, num_experts, capacity), jnp.float32)
    counts = jnp.zeros((num_experts,), jnp.int32)
    for slot in range(k):
        oh = jax.nn.one_hot(top_i[:, slot], num_experts, dtype=jnp.int32)  # [T,E]
        pos = counts[None, :] + jnp.cumsum(oh, axis=0) - oh  # [T,E]
        pos_t = jnp.sum(pos * oh, axis=1)  # [T] position within chosen expert
        keep = pos_t < capacity
        pos_oh = jax.nn.one_hot(pos_t, capacity, dtype=jnp.float32) * keep[:, None]
        combine = combine + (top_p[:, slot][:, None, None]
                             * oh[:, :, None] * pos_oh[:, None, :])
        counts = counts + jnp.sum(oh * keep[:, None], axis=0)
    dispatch = combine > 0
    return combine, dispatch


def moe_layer(x, gate_w, expert_fn: Callable, expert_params, mesh: Mesh, *,
              axis_name: str = "expert", num_experts: int, top_k: int = 2,
              capacity_factor: float = 1.5):
    """Mixture-of-experts layer with expert parallelism.

    x: [B, S, D] (replicated or data-sharded over other axes)
    gate_w: [D, E] router weights (replicated)
    expert_params: pytree with leading dim E, sharded P(axis_name) — each
        device holds E/n experts.
    expert_fn(params_one_expert, tokens [N, D]) -> [N, D]
    """
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    n_tok = b * s
    capacity = max(1, int(n_tok * capacity_factor * top_k / num_experts))

    gate_logits = tokens @ gate_w  # [T, E]
    top_p, top_i = top_k_gating(gate_logits, top_k)
    combine, dispatch = _dispatch_masks(top_i, top_p, num_experts, capacity)

    # [T,E,C] x [T,D] -> [E,C,D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), tokens)

    if axis_name in mesh.axis_names and mesh.shape[axis_name] > 1:
        n = mesh.shape[axis_name]
        e_local = num_experts // n

        def sharded(expert_in, expert_params):
            # expert_in arrives token-replicated [E, C, D]; keep only local
            # experts' buckets — no all_to_all needed when tokens replicated.
            idx = jax.lax.axis_index(axis_name)
            local = jax.lax.dynamic_slice_in_dim(expert_in, idx * e_local,
                                                 e_local, axis=0)
            out = jax.vmap(expert_fn)(
                jax.tree.map(lambda p: p, expert_params), local)  # [e_local, C, D]
            # gather all experts' outputs back (all-gather over expert axis)
            full = jax.lax.all_gather(out, axis_name, axis=0, tiled=True)
            return full  # [E, C, D]

        param_specs = jax.tree.map(lambda _: P(axis_name), expert_params)
        expert_out = shard_map(
            sharded, mesh=mesh, in_specs=(P(), param_specs), out_specs=P(),
            check=False)(expert_in, expert_params)
    else:
        expert_out = jax.vmap(expert_fn)(expert_params, expert_in)

    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return out.reshape(b, s, d)


def moe_layer_tokens_sharded(x, gate_w, expert_fn: Callable, expert_params,
                             mesh: Mesh, *, axis_name: str = "expert",
                             num_experts: int, top_k: int = 2,
                             capacity_factor: float = 1.5):
    """MoE with tokens ALSO sharded over the expert axis (the scalable form):
    each device routes its token shard, then a ragged `all_to_all` exchanges
    token buckets for expert shards — this is the ICI-native analog of the
    reference delegating MoE to per-actor NCCL groups."""
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        return moe_layer(x, gate_w, expert_fn, expert_params, mesh,
                         axis_name=axis_name, num_experts=num_experts,
                         top_k=top_k, capacity_factor=capacity_factor)
    n = mesh.shape[axis_name]
    e_local = num_experts // n

    def sharded(x_local, gate_w, expert_params):
        b, s, d = x_local.shape
        tokens = x_local.reshape(b * s, d)
        n_tok = b * s
        capacity = max(1, int(n_tok * capacity_factor * top_k / num_experts))
        gate_logits = tokens @ gate_w
        top_p, top_i = top_k_gating(gate_logits, top_k)
        combine, dispatch = _dispatch_masks(top_i, top_p, num_experts, capacity)
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x_local.dtype), tokens)
        # [E, C, D] -> split expert dim across devices, concat bucket dim:
        # result [E/n, n*C, D]: local experts' buckets from every token shard
        ein = jax.lax.all_to_all(expert_in, axis_name, split_axis=0,
                                 concat_axis=1, tiled=True)
        out = jax.vmap(expert_fn)(expert_params, ein)  # [E/n, n*C, D]
        # reverse exchange: [E/n, n*C, D] -> [E, C, D] (local tokens' results)
        eout = jax.lax.all_to_all(out, axis_name, split_axis=1,
                                  concat_axis=0, tiled=True)
        res = jnp.einsum("tec,ecd->td", combine.astype(x_local.dtype), eout)
        return res.reshape(b, s, d)

    param_specs = jax.tree.map(lambda _: P(axis_name), expert_params)
    return shard_map(
        sharded, mesh=mesh,
        in_specs=(P(axis_name), P(), param_specs), out_specs=P(axis_name),
        check=False)(x, gate_w, expert_params)
