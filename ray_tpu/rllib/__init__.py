"""RL library: parallel rollout collection on actors + jitted learners.

TPU-native rebuild of the reference's RLlib core
(/root/reference/rllib/ — algorithms/, core/rl_module/, env/): EnvRunner
actors sample on CPU, learning is a jitted JAX step, weights broadcast
through the object store. Ships PPO and DQN on the new API stack surface
(AlgorithmConfig fluent builder -> Algorithm.train()).
"""

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.buffer import ReplayBuffer
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env import CartPole, Env, RandomWalk, make_env, register_env
from ray_tpu.rllib.env_runner import EnvRunner, EnvRunnerGroup
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.models import RLModule
from ray_tpu.rllib.multi_agent import (
    MatchingGame,
    MultiAgentEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
)
from ray_tpu.rllib.offline import (
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    IQL,
    IQLConfig,
    MARWIL,
    MARWILConfig,
    OfflineData,
    record_episodes,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = [
    "Algorithm", "AlgorithmConfig", "APPO", "APPOConfig", "ReplayBuffer",
    "DQN", "DQNConfig", "MatchingGame", "MultiAgentEnv",
    "MultiAgentEnvRunner", "MultiAgentPPO",
    "CartPole", "Env", "RandomWalk", "make_env", "register_env",
    "EnvRunner", "EnvRunnerGroup", "IMPALA", "IMPALAConfig", "RLModule",
    "PPO", "PPOConfig", "SAC", "SACConfig", "BC", "BCConfig", "CQL",
    "CQLConfig", "IQL", "IQLConfig", "MARWIL", "MARWILConfig",
    "OfflineData", "record_episodes",
]
