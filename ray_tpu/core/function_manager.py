"""Function/actor-class export via the control-plane KV.

TPU-native analog of the reference's function manager
(/root/reference/python/ray/_private/function_manager.py): the driver exports
cloudpickled functions/classes to the control plane's KV keyed by a content
hash; executors fetch and cache them on first use.
"""

from __future__ import annotations

import hashlib
import threading
import time

import cloudpickle


class FunctionManager:
    def __init__(self, runtime):
        self._rt = runtime
        self._cache: dict[str, object] = {}
        self._exported: set[str] = set()
        self._lock = threading.Lock()

    def export(self, fn) -> str:
        blob = cloudpickle.dumps(fn)
        function_id = hashlib.sha1(blob).hexdigest()
        with self._lock:
            if function_id in self._exported:
                return function_id
        self._rt.cp_client.call_with_retry(
            "kv_put", {"key": f"fn:{function_id}", "value": blob, "overwrite": False},
            timeout=30.0)
        with self._lock:
            self._exported.add(function_id)
            self._cache.setdefault(function_id, cloudpickle.loads(blob))
        return function_id

    def get(self, function_id: str, timeout: float = 30.0):
        with self._lock:
            fn = self._cache.get(function_id)
        if fn is not None:
            return fn
        deadline = time.monotonic() + timeout
        while True:
            blob = self._rt.cp_client.call_with_retry(
                "kv_get", {"key": f"fn:{function_id}"}, timeout=10.0)
            if blob is not None:
                fn = cloudpickle.loads(blob)
                with self._lock:
                    self._cache[function_id] = fn
                return fn
            if time.monotonic() > deadline:
                raise TimeoutError(f"function {function_id} not found in KV")
            time.sleep(0.05)
