"""Checkpoint handle + storage + top-K manager.

TPU-native analog of the reference's checkpoint stack
(/root/reference/python/ray/train/_checkpoint.py:56 Checkpoint-as-directory,
train/v2/_internal/execution/storage.py StorageContext +
_pyarrow_fs_copy_files:99, checkpoint/checkpoint_manager.py:78 top-K
retention). Payload writing on TPU is expected to go through Orbax inside the
user train fn; this layer only moves directories and tracks lineage — the
same division of labor as the reference.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import tempfile
import threading
import uuid
from typing import Optional


class Checkpoint:
    """A directory full of checkpoint payload, addressed by path.

    Like the reference's Checkpoint (train/_checkpoint.py:56) this is a thin
    handle: `path` + helpers, no format opinion. Local filesystem paths only
    in-tree (cloud fs can be layered via the same API).
    """

    def __init__(self, path: str):
        self.path = os.fspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint payload into `path` (or a temp dir) and return it."""
        dest = path or tempfile.mkdtemp(prefix="ckpt_")
        if os.path.abspath(dest) != os.path.abspath(self.path):
            os.makedirs(dest, exist_ok=True)
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def update_metadata(self, metadata: dict) -> None:
        meta = self.get_metadata()
        meta.update(metadata)
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(meta, f)

    def get_metadata(self) -> dict:
        p = os.path.join(self.path, ".metadata.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and \
            os.path.abspath(self.path) == os.path.abspath(other.path)

    def __hash__(self):
        return hash(os.path.abspath(self.path))


class StorageContext:
    """Resolves the run's persistent directory layout.

    Layout (mirrors the reference storage.py):
        {storage_path}/{run_name}/checkpoint_{index:06d}/...
        {storage_path}/{run_name}/result.json
    """

    def __init__(self, storage_path: str, run_name: str):
        self.storage_path = os.fspath(storage_path)
        self.run_name = run_name
        self.run_path = os.path.join(self.storage_path, run_name)
        os.makedirs(self.run_path, exist_ok=True)

    def checkpoint_dir(self, index: int) -> str:
        return os.path.join(self.run_path, f"checkpoint_{index:06d}")

    def persist(self, checkpoint: Checkpoint, index: int) -> Checkpoint:
        """Move (same filesystem) or copy a worker-local checkpoint dir into
        persistent storage — moving avoids leaving dead payload dirs behind
        in /tmp for the life of the run."""
        dest = self.checkpoint_dir(index)
        if os.path.abspath(checkpoint.path) == os.path.abspath(dest):
            return checkpoint
        if os.path.exists(dest):
            shutil.rmtree(dest)
        _move_or_copy(checkpoint.path, dest)
        return Checkpoint(dest)


@dataclasses.dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    metrics: dict
    index: int


class CheckpointManager:
    """Top-K checkpoint retention ordered by a score metric.

    Reference: train/v2/_internal/execution/checkpoint/checkpoint_manager.py:78.
    """

    def __init__(self, storage: StorageContext, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self._storage = storage
        self._num_to_keep = num_to_keep
        self._score_attr = score_attribute
        self._score_order = score_order
        self._lock = threading.Lock()
        self._index = 0
        self._checkpoints: list[_TrackedCheckpoint] = []
        self.latest: Optional[_TrackedCheckpoint] = None

    def register(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        """Persist a reported checkpoint; evict beyond top-K. Returns the
        persisted handle."""
        with self._lock:
            idx = self._index
            self._index += 1
            persisted = self._storage.persist(checkpoint, idx)
            tracked = _TrackedCheckpoint(persisted, dict(metrics), idx)
            self._checkpoints.append(tracked)
            self.latest = tracked
            self._evict()
            return persisted

    def register_sharded(self, rank_checkpoints: list, metrics: dict,
                         world_size: int) -> Checkpoint:
        """Merge per-rank shard checkpoints into ONE sharded checkpoint
        (layout: shard-{rank:05d}/ subdirs + metadata), the controller-side
        half of Orbax-style distributed writes (SURVEY.md §5.4). Ranks wrote
        their shards in parallel (each a local dir); here they only get
        moved under a common index directory."""
        with self._lock:
            idx = self._index
            self._index += 1
            dest = self._storage.checkpoint_dir(idx)
            os.makedirs(dest, exist_ok=True)
            for rank, ckpt in rank_checkpoints:
                _move_or_copy(ckpt.path,
                              os.path.join(dest, f"shard-{rank:05d}"))
            merged = Checkpoint(dest)
            merged.update_metadata(
                {"sharded": True, "world_size": world_size,
                 "num_shards": len(rank_checkpoints)})
            tracked = _TrackedCheckpoint(merged, dict(metrics), idx)
            self._checkpoints.append(tracked)
            self.latest = tracked
            self._evict()
            return merged

    def _score(self, t: _TrackedCheckpoint):
        if self._score_attr is None:
            return t.index  # recency
        val = t.metrics.get(self._score_attr)
        if val is None:
            return float("-inf") if self._score_order == "max" else float("inf")
        return val

    def _evict(self):
        if self._num_to_keep is None or len(self._checkpoints) <= self._num_to_keep:
            return
        reverse = self._score_order == "max"
        ranked = sorted(self._checkpoints, key=self._score, reverse=reverse)
        keep = set(id(t) for t in ranked[: self._num_to_keep])
        # Never evict the latest (needed for resume).
        keep.add(id(self.latest))
        survivors = []
        for t in self._checkpoints:
            if id(t) in keep:
                survivors.append(t)
            else:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._checkpoints = survivors

    def best_checkpoints(self) -> list[tuple[Checkpoint, dict]]:
        with self._lock:
            reverse = self._score_order == "max"
            ranked = sorted(self._checkpoints, key=self._score, reverse=reverse)
            return [(t.checkpoint, t.metrics) for t in ranked]

    def write_state(self):
        """Persist manager state for resume-after-driver-crash."""
        state = {
            "index": self._index,
            "checkpoints": [
                {"path": t.checkpoint.path, "metrics": t.metrics, "index": t.index}
                for t in self._checkpoints
            ],
            "latest": self.latest.index if self.latest else None,
        }
        with open(os.path.join(self._storage.run_path, "manager_state.json"),
                  "w") as f:
            json.dump(state, f)

    @classmethod
    def restore_state(cls, storage: StorageContext, **kwargs) -> "CheckpointManager":
        mgr = cls(storage, **kwargs)
        p = os.path.join(storage.run_path, "manager_state.json")
        if os.path.exists(p):
            with open(p) as f:
                state = json.load(f)
            mgr._index = state["index"]
            for rec in state["checkpoints"]:
                if os.path.exists(rec["path"]):
                    t = _TrackedCheckpoint(Checkpoint(rec["path"]),
                                           rec["metrics"], rec["index"])
                    mgr._checkpoints.append(t)
                    if state["latest"] == rec["index"]:
                        mgr.latest = t
        return mgr


class AsyncCheckpointWriter:
    """Background checkpoint writes: the train step keeps running while the
    payload lands on disk (the async half of Orbax-style checkpointing,
    SURVEY.md §5.4). One write in flight at a time; a new write waits for
    the previous one, and report() fires only after the payload is durable
    (so the controller never copies a half-written directory).

    Usage inside a train fn:
        writer = AsyncCheckpointWriter()
        ...
        writer.write_and_report(save_fn, metrics)   # save_fn(dir_path)
        ...
        writer.finish()   # before returning from the train fn
    """

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(1, thread_name_prefix="ckpt-writer")
        self._last = None

    def write_and_report(self, save_fn, metrics: dict) -> None:
        from ray_tpu.train import context as _ctx

        self.wait()
        ctx = _ctx.get_context()

        def job():
            path = tempfile.mkdtemp(prefix="ckpt_async_")
            save_fn(path)
            ctx.report(dict(metrics), Checkpoint(path))

        self._last = self._pool.submit(job)

    def wait(self) -> None:
        if self._last is not None:
            self._last.result()
            self._last = None

    def finish(self) -> None:
        self.wait()
        self._pool.shutdown(wait=True)


def _move_or_copy(src: str, dest: str) -> None:
    """Move single-use temp payloads (frees the source — no dead dirs
    accumulating in /tmp for the life of the run); copy anything the caller
    might still reference (non-temp paths)."""
    tmp = os.path.abspath(tempfile.gettempdir())
    src_abs = os.path.abspath(src)
    if src_abs.startswith(tmp + os.sep):
        try:
            os.replace(src_abs, dest)
            return
        except OSError:
            pass
    shutil.copytree(src_abs, dest, dirs_exist_ok=True)


def new_run_name() -> str:
    return "run_" + uuid.uuid4().hex[:10]
