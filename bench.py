"""Benchmark: Llama pretraining step throughput (tokens/sec/chip).

North-star metric per BASELINE.json ("Ray Train tokens/sec/chip @
Llama-3-8B"); the reference repo publishes no number for it ("published": {}),
so vs_baseline is reported against the theoretical MXU roofline instead:
model-FLOPs utilization (MFU), where 1.0 = peak bf16 matmul throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs on whatever single chip is visible (TPU via axon, else CPU fallback with
a tiny model so the harness always produces a result).
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


# bf16 peak TFLOP/s per chip for MFU reporting (best-effort device match)
_PEAK_TFLOPS = {
    "v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0,
}


def _peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    return _PEAK_TFLOPS["v5e"]  # conservative default


def main() -> None:
    from ray_tpu.models import llama
    from ray_tpu.train import spmd

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = llama.llama3_1b(max_seq_len=2048)
        batch, seq, steps, warmup = 8, 1024, 10, 3
    else:
        cfg = llama.llama_tiny()
        batch, seq, steps, warmup = 8, 64, 5, 2

    mesh = spmd.make_mesh(1, devices=[dev])
    opt = spmd.default_optimizer(warmup_steps=10, decay_steps=1000)
    state, sh = spmd.sharded_create_state(
        lambda: llama.init_params(jax.random.PRNGKey(0), cfg), opt, mesh,
        params_logical_axes=llama.logical_axes(cfg))
    step = spmd.make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg, mesh), opt, mesh, sh)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)
    batch_data = spmd.shard_batch({"tokens": tokens}, mesh)

    # NOTE: force a device->host transfer as the sync barrier —
    # block_until_ready is not a reliable fence over the axon tunnel.
    for _ in range(warmup):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tok_per_s = batch * seq * steps / dt
    # MFU: 6 * params * tokens/sec forward+backward matmul FLOPs
    n_params = llama.num_params(cfg)
    mfu = (6.0 * n_params * tok_per_s) / (_peak_tflops(dev) * 1e12) \
        if on_tpu else 0.0

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 4) if on_tpu else None,
    }))


if __name__ == "__main__":
    sys.exit(main())
