"""Aggregation functions for groupby (reference:
/root/reference/python/ray/data/aggregate.py — AggregateFn, Count, Sum, Min,
Max, Mean, Std, plus grouped_data.py's dispatch)."""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, block_from_rows


@dataclasses.dataclass
class AggregateFn:
    name: str
    on: Optional[str]
    compute: Callable[[np.ndarray], float]

    def out_name(self) -> str:
        return f"{self.name}({self.on})" if self.on else self.name


def Count(on: Optional[str] = None) -> AggregateFn:
    return AggregateFn("count", on, lambda v: int(len(v)))


def Sum(on: str) -> AggregateFn:
    return AggregateFn("sum", on, lambda v: v.sum())


def Min(on: str) -> AggregateFn:
    return AggregateFn("min", on, lambda v: v.min())


def Max(on: str) -> AggregateFn:
    return AggregateFn("max", on, lambda v: v.max())


def Mean(on: str) -> AggregateFn:
    return AggregateFn("mean", on, lambda v: v.mean())


def Std(on: str, ddof: int = 1) -> AggregateFn:
    return AggregateFn("std", on, lambda v: v.std(ddof=ddof))


def apply_aggs(table: Block, key: Optional[str], aggs: list[AggregateFn]) -> Block:
    acc = BlockAccessor.for_block(table)
    if acc.num_rows() == 0:
        return pa.table({})
    if key is None:
        row = {}
        for agg in aggs:
            col = (acc.column_to_numpy(agg.on) if agg.on
                   else np.arange(acc.num_rows()))
            row[agg.out_name()] = agg.compute(col)
        return block_from_rows([row])
    keys = acc.column_to_numpy(key)
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    uniq, starts = np.unique(sorted_keys, return_index=True)
    rows = []
    for i, k in enumerate(uniq):
        lo = starts[i]
        hi = starts[i + 1] if i + 1 < len(starts) else len(sorted_keys)
        idx = order[lo:hi]
        row = {key: k.item() if hasattr(k, "item") else k}
        for agg in aggs:
            col = (acc.column_to_numpy(agg.on)[idx] if agg.on
                   else np.arange(len(idx)))
            val = agg.compute(col)
            row[agg.out_name()] = val.item() if hasattr(val, "item") else val
        rows.append(row)
    return block_from_rows(rows)
