"""Continuous-batching LLM engine (TPU-native vLLM-engine analog).

Matches the role of the reference's VLLMEngine
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:101):
requests enter a waiting queue; the engine loop admits them into fixed
decode slots (prefill), then every iteration runs ONE fused decode step
across all active slots and streams sampled tokens out per request.

TPU-first properties:
- the decode step is a single jitted program with static shapes
  ([max_batch_size] slots, fixed page table width) — compiled once;
- prefill pads prompts to power-of-two length buckets, so at most
  log2(max_prompt_len) prefill programs ever compile;
- KV lives in a paged HBM pool (kv_cache.py) so long and short requests
  share memory; page exhaustion simply delays admission (no OOM);
- sampling (greedy/temperature/top-k) happens on device; only the sampled
  token ids [B] come back to the host each step.

Threading model: the engine owns a single loop thread (the TPU admits one
process; within it one thread drives the device). `submit()` / `drain()` /
`result()` are thread-safe and may be called from replica request handlers.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ray_tpu.observability import events as _fr
from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.tokenizer import get_tokenizer

logger = logging.getLogger(__name__)


def kv_tier_namespace(cfg: LLMConfig, model_cfg, kv_dtype,
                      rng_seed: int = 0) -> str:
    """Cluster-index namespace for a config's KV pages. A chain digest
    encodes the token prefix, NOT which model computed the KV — two
    architecturally identical models would cross-restore each other's
    pages and silently decode garbage. Scope the index to everything
    that makes KV bytes interchangeable: model id, weights (checkpoint
    path, or the init seed for random weights), architecture config, KV
    dtype, page size. Shared by LLMEngine and the disagg PrefillServer
    (ISSUE 16): both sides deriving the namespace from the same config
    is what lets a prefill replica's spills be visible to decode
    replicas' restores."""
    ident = "|".join([
        str(cfg.model_id),
        str(cfg.checkpoint_path or f"seed:{rng_seed}"),
        repr(model_cfg),
        str(cfg.page_size),
        str(kv_dtype)])
    if cfg.kv_tier_codec == "int8":
        # lossy pages are NOT interchangeable with exact ones: a
        # lossless replica restoring quantized KV would silently break
        # its bit-identity guarantee, so quantized stores index under
        # their own namespace. none<->lossless mix freely (both decode
        # to identical bytes).
        ident += "|int8"
    if getattr(cfg, "tp_degree", 1) > 1:
        # sharding layout is part of the codec identity (ISSUE 20), same
        # precedent as |int8: a TP engine writes mode="shards" blobs
        # split per-KV-head at its tp_degree, and replicas with
        # different layouts index under different namespaces so byte
        # accounting, AB comparisons and fleet warm-starts never mix
        # blob layouts. TP=1 omits the suffix so existing single-chip
        # namespaces — and every already-spilled blob — stay valid.
        ident += f"|tp{int(cfg.tp_degree)}"
    return hashlib.sha256(ident.encode()).hexdigest()[:16]


@dataclass
class _Request:
    request_id: str
    prompt_tokens: list[int]
    max_tokens: int
    temperature: float
    top_k: int
    stop_token: Optional[int]
    # state
    slot: int = -1
    pages: list[int] = field(default_factory=list)
    generated: list[int] = field(default_factory=list)
    dispatched: int = 0  # tokens whose computation has been dispatched
    prefill_pos: int = 0  # prompt tokens already prefilled (chunked prefill)
    # prompt tokens served from the prefix cache (shared pages; prefill_pos
    # starts here so only the suffix is computed)
    cached_tokens: int = 0
    # cancelled/shed while mid chunked prefill: the loop frees slot+pages
    # promptly via _abort_prefilling instead of finishing the prompt pass
    prefill_cancelled: bool = False
    # speculative decoding: per-request n-gram proposer (spec_decode.py),
    # created lazily on the first draft attempt; spec_inflight marks a slot
    # with an unharvested verify round so the decode path never dispatches
    # it concurrently (its device seq_len is k+1 ahead until rollback)
    spec: Any = None
    spec_inflight: bool = False
    drained_upto: int = 0
    done: bool = False
    error: Optional[str] = None
    submitted_at: float = field(default_factory=time.monotonic)
    # set under the lock when _admit pops this request off _waiting; the
    # submit→admit gap is the queue wait surfaced in result()/engine_stats
    admitted_at: Optional[float] = None
    # KV-tier restore accounting (ISSUE 12 attribution): tokens whose KV
    # came back from the tier, decoded payload size, and the restore
    # wall time (stream open -> finalize; the stream overlaps other
    # requests' work, so wall != loop time — see restore_blocked_ms)
    restored_tokens: int = 0
    restore_bytes: int = 0
    restore_ms: float = 0.0
    # streaming restore (ISSUE 15): the live ChainStream while this
    # request sits in _restoring, plus its attribution split — encoded
    # bytes off the wire, codec decode time, loop time actually spent
    # on this stream (take/decode/inject); overlap = wall - blocked,
    # i.e. how much restore latency hid under other engine work
    restore_stream: Any = None
    restore_started: float = 0.0        # perf_counter at stream open
    restore_page0: int = 0              # first chain slot the stream fills
    restore_pages: int = 0              # pages injected so far
    restore_wire_bytes: int = 0
    restore_decode_ms: float = 0.0
    restore_blocked_ms: float = 0.0
    restore_overlap_ms: float = 0.0
    # stream ended short of its plan (peer death / chunk timeout): the
    # landed pages were kept and the tail re-prefilled (ISSUE 16)
    restore_partial: bool = False
    # fleet disagg handoff (ISSUE 16): the prompt KV was prefilled by a
    # remote prefill replica and registered in the tier before this
    # submit — the restore this request performs IS the handoff, so its
    # wire/overlap numbers feed the disagg engine counters
    disagg: bool = False
    first_token_at: Optional[float] = None
    # inter-token latency: host record-time of the last token plus the
    # per-token gaps (pipelined harvests record blocks in bursts, so the
    # gap distribution shows the streaming cadence a drain() consumer
    # actually sees — k-1 near-zero gaps then one block-sized one)
    last_token_at: Optional[float] = None
    itl_gaps: list[float] = field(default_factory=list)
    finished_at: Optional[float] = None
    done_event: threading.Event = field(default_factory=threading.Event)
    # distributed tracing: carrier captured at submit (the engine loop
    # thread has no ambient span context), wall-clock start for the span
    trace_ctx: Optional[dict] = None
    submitted_wall: float = field(default_factory=time.time)
    # end-to-end request deadline (core/deadline.py, epoch seconds),
    # captured at submit: the admission loop sheds waiting requests whose
    # deadline passed instead of prefilling answers nobody will read
    deadline: Optional[float] = None
    # leading page-chain digests (hex) computed at serve ingress (ISSUE
    # 10): _kv_tier_restore reuses them instead of re-hashing the prompt,
    # after verifying page 0 against a local recompute (a tokenizer
    # mismatch between ingress and engine must degrade to the recompute
    # path, never restore wrong KV)
    ingress_digests: Optional[list] = None
    # mid-stream failover (ISSUE 14): number of already-generated tokens
    # from the dead replica appended to prompt_tokens as a continuation
    # spec. 0 = ordinary request. The admission path is unchanged — the
    # continuation rides the same prefix-match / tier-restore / chunked
    # suffix prefill machinery, and decode resumes at the exact next
    # token (greedy continuations are bit-identical to an uninterrupted
    # run: same KV prefix, same argmax).
    resume_len: int = 0


class LLMEngine:
    def __init__(self, cfg: LLMConfig, params=None, rng_seed: int = 0):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama
        from ray_tpu.serve.llm import kv_cache as kvc

        self.cfg = cfg
        self.model_cfg = cfg.llama()
        self.tokenizer = get_tokenizer(cfg.tokenizer)
        self._jax = jax
        self._jnp = jnp
        self._kvc = kvc
        # Paged-attention backend, resolved ONCE (ops/paged_attention.py
        # fused kernels vs the materialized-gather path). Static for the
        # engine's lifetime: it's baked into every compiled program, and
        # resolving here keeps the jitted impls free of backend probing.
        self._attn_backend = kvc.resolve_attention_backend(
            cfg.attention_kernel, self.model_cfg, cfg.page_size)

        if params is None:
            if cfg.checkpoint_path:
                params = llama.load_params(cfg.checkpoint_path,
                                           self.model_cfg)
            else:
                params = llama.init_params(
                    jax.random.PRNGKey(rng_seed), self.model_cfg)
        self.params = params

        b = cfg.max_batch_size
        self.max_pages_per_seq = -(-cfg.max_seq_len // cfg.page_size)
        self.kv = kvc.init_paged_cache(
            self.model_cfg, cfg.num_pages, cfg.page_size)
        # Tensor parallelism (ISSUE 20): one engine process drives a
        # tp_degree-chip "tensor" mesh. Weights get Megatron-style
        # partition-rule shardings (parallel/sharding.py — the SAME
        # match_partition_rules train/spmd.py uses), the page pool is
        # split per-KV-head, and everything else about the engine — the
        # loop, the allocator, page tables, the tier — keeps operating on
        # whole-replica logical state. tp_degree=1 builds no mesh and
        # compiles the exact single-chip programs (bit-identical to a
        # pre-TP engine).
        self._tp = max(1, int(getattr(cfg, "tp_degree", 1)))
        self._mesh = None
        if self._tp > 1:
            self._mesh = self._setup_tp_mesh()
        # performance introspection (observability/profiling.py): phase
        # timers + ITL ring gate on cfg.profiling_enabled; compile-event
        # tracking is always on (work only on first-dispatch-per-shape).
        # Weights/KV-pool byte accounting is shape*dtype math — the KV
        # pool is donated every step but its layout never changes.
        from ray_tpu.observability import profiling as profiling_mod
        self._prof = profiling_mod.EngineProfiler(
            enabled=bool(cfg.profiling_enabled))
        self._prof.set_memory_layout(
            profiling_mod.tree_bytes(self.params),
            profiling_mod.tree_bytes(self.kv))
        # Prefix caching (see kv_cache.PageAllocator): all bookkeeping is
        # host-side between steps — the page table indirection means shared
        # pages change WHICH pool pages a slot reads, never the compiled
        # programs or their shapes.
        self._prefix_cache_on = bool(cfg.prefix_cache_enabled)
        # one-shot log guard: ingress digests disagreeing with the local
        # recompute (tokenizer skew) warns once, not once per request
        self._ingress_skew_warned = False
        self.allocator = kvc.PageAllocator(
            cfg.num_pages, cache_pages=cfg.prefix_cache_max_pages)
        self.page_tables = np.zeros((b, self.max_pages_per_seq), np.int32)
        self.seq_lens = np.zeros((b,), np.int32)
        self.slot_req: list[Optional[_Request]] = [None] * b
        self.free_slots = list(range(b))

        self._lock = threading.Lock()
        self._waiting: list[_Request] = []
        # chunked prefill: admitted (slot+pages held) but prompt not fully
        # prefilled; the loop dispatches one chunk per request per iteration
        # interleaved with decode blocks, so a long admission never stalls
        # active generations for its whole prompt pass
        self._prefilling: list[_Request] = []
        # streaming tier restore (ISSUE 15): admitted (slot+pages held),
        # restore stream open — the loop decodes+injects landed chunks
        # (_restore_steps) and routes each request on to its suffix
        # prefill when the stream ends (fully or partially)
        self._restoring: list[_Request] = []
        self._requests: dict[str, _Request] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._rng = jax.random.PRNGKey(rng_seed + 1)
        self._loop_thread: Optional[threading.Thread] = None
        self.stats = {"steps": 0, "prefills": 0, "tokens_out": 0,
                      "requests": 0, "shed_expired": 0, "compile_s": 0.0,
                      "prefix_hits": 0, "prefix_misses": 0,
                      "prefix_hit_tokens": 0,
                      "spilled_pages": 0, "restored_pages": 0,
                      "tier_hit_tokens": 0, "restore_partial": 0,
                      "spec_rounds": 0, "spec_drafted_tokens": 0,
                      "spec_accepted_tokens": 0,
                      "failover_resumed": 0, "failover_restored_tokens": 0,
                      "disagg_prefills": 0, "handoff_bytes_wire": 0,
                      "handoff_overlap_ms": 0.0,
                      "warm_start_pages": 0, "warm_start_ms": 0.0,
                      # per-kernel dispatch counters (ISSUE 18): how many
                      # decode / verify / chunk programs — each containing
                      # the resolved attention backend's kernels — this
                      # engine dispatched; paired with attention_backend
                      # so a fleet mixing gather/pallas replicas is
                      # visible per replica
                      "attn_decode_dispatches": 0,
                      "attn_verify_dispatches": 0,
                      "attn_chunk_dispatches": 0}
        # Tiered KV cache (kv_tier.py): evicted cached page chains spill
        # host-side into a shm/disk tier + cluster index instead of dying,
        # and _admit extends its longest-match search past the local index
        # into the tier. The allocator hook only CAPTURES evictions and
        # dispatches one device gather (stream-ordered before any reuse of
        # the pages); the host copy + object-store put happen later on the
        # loop, off the admission hot path (_kv_tier_flush).
        self._kv_tier_on = bool(cfg.kv_tier_enabled) and self._prefix_cache_on
        self._kv_tier = None
        self._tier_pending: list = []  # [(dev_k, dev_v, [(page, dig, pos)])]
        # drain-time eager spill handshake (ISSUE 14): spill_inflight()
        # parks one (done_event, result_box) here and the loop performs
        # the gather+flush — the device stream has exactly one driver
        self._spill_req: Optional[tuple] = None
        # cache-warm scale-up handshake (ISSUE 17): warm_start() parks
        # (done_event, result_box, max_bytes, budget_s) here — same
        # one-driver discipline; the restore injections run on the loop
        self._warm_req: Optional[tuple] = None
        if self._kv_tier_on:
            from ray_tpu.serve.llm import kv_tier as kvt
            self._kv_tier = kvt.KVTierStore(
                max_bytes=cfg.kv_tier_max_bytes,
                disk_dir=cfg.kv_tier_disk_dir,
                disk_max_bytes=cfg.kv_tier_disk_max_bytes,
                ttl_s=cfg.kv_tier_ttl_s,
                page_size=cfg.page_size,
                namespace=kv_tier_namespace(
                    cfg, self.model_cfg, self.kv["k"].dtype, rng_seed),
                codec=cfg.kv_tier_codec,
                # per-shard encoded sub-payloads under ONE chain digest
                # (ISSUE 20): the namespace above already carries |tp{N}
                # so layouts never mix across stores
                shards=self._tp)
            self.allocator.spill_hook = self._spill_capture
            # restore scatter at ONE fixed shape (max_pages_per_seq,
            # trash-page padded) — same donated-pool pattern as disagg's
            # _inject; an eager per-count scatter would compile per
            # distinct restored-page count
            self._tier_inject = jax.jit(
                lambda kv, bk, bv, pages: {
                    "k": kv["k"].at[:, :, pages].set(bk),
                    "v": kv["v"].at[:, :, pages].set(bv)},
                donate_argnums=(0,))
        # Speculative decoding (spec_decode.py + the verify-k program
        # below): host-side n-gram drafts verified k-at-a-time in one
        # fused dispatch. Greedy-only guarantee — non-greedy slots are
        # never drafted and ride the normal decode path.
        self._spec_on = bool(cfg.spec_decode_enabled)
        # last decode-block k actually dispatched + live pipeline depth
        # (engine_stats gauges: the k=1/pressure/full tier transitions are
        # observable instead of inferred from throughput wiggles)
        self._last_block = 0
        # Probe ONCE whether this jax exposes Array.is_ready(): the old
        # per-call AttributeError fallback silently returned False forever,
        # disabling eager harvest for the whole process on older jax. With
        # no readiness API the loop instead runs a bounded harvest (see
        # _loop): pop the oldest block while at least one newer block is
        # already dispatched behind it on the ordered device stream.
        self._is_ready_supported = hasattr(
            jnp.zeros((), jnp.int32), "is_ready")
        # Pipelined decode (vLLM-style async token processing, re-shaped for
        # a REMOTE chip): each step's input tokens are the previous step's
        # on-device output, so steps dispatch back-to-back without a host
        # sync — the host harvests sampled tokens PIPELINE_DEPTH steps
        # behind. Token latency then tracks step execution time instead of
        # the host<->device round trip (which dominates through the axon
        # tunnel: ~280ms/step synced vs ~10-30ms/step pipelined).
        self.PIPELINE_DEPTH = cfg.pipeline_depth
        self._pending: list = []   # [(dev_tokens, [(col, slot, req)], k)]
        self._dev_tokens = None    # [B+1] device array (incl. trash row)
        self._overrides: dict[int, int] = {}  # slot -> first token (prefill)
        # device-resident decode state (page tables / seq lens / temps);
        # slot admissions mark entries dirty and patch them with one small
        # update before the next dispatch. Row b (one past the last slot)
        # is a PERMANENT TRASH ROW: bucketed dispatch pads its packed slot
        # index vector with it, so padding lanes write into the trash page
        # (page-table row of zeros) instead of any live slot's KV.
        self._pt_dev = jnp.zeros((b + 1, self.max_pages_per_seq), jnp.int32)
        self._sl_dev = jnp.zeros((b + 1,), jnp.int32)
        self._temps_dev = jnp.zeros((b + 1,), jnp.float32)
        if self._mesh is not None:
            # replicate-commit the small decode state on the TP mesh so
            # the donated state buffers keep one deterministic layout
            # step to step (uncommitted operands would let each program's
            # first compile pick, and donation would then pin whatever it
            # guessed)
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self._mesh, P())
            self._pt_dev, self._sl_dev, self._temps_dev = jax.device_put(
                (self._pt_dev, self._sl_dev, self._temps_dev), rep)
        self._dirty_slots: dict[int, tuple] = {}  # slot -> (seq_len, temp)

        # jitted programs. The KV pool is DONATED: it's the dominant HBM
        # allocation and the step rewrites it in place — without donation
        # every step would materialize a second full pool (2x HBM + a full
        # pool copy of bandwidth per token). The decode program gathers the
        # packed active rows by index on device, runs the fused block at the
        # PACKED width, and scatters the carried state back — one program
        # per (bucket width, block length), so a lightly loaded engine pays
        # for the requests it has, not for max_batch_size.
        self._decode = jax.jit(
            lambda params, kv, pt, sl, toks, rng, temp, idx, n:
            self._decode_impl(params, kv, pt, sl, toks, rng, temp, idx, n),
            donate_argnums=(1, 3, 4), static_argnums=(8,))
        # verify-k (speculative decoding): same packed-width shape as
        # _decode, but the scan consumes the DRAFTED tokens instead of its
        # own samples; the draft length is static via drafts.shape — one
        # verify program per bucket width, ever.
        self._verify = jax.jit(
            lambda params, kv, pt, sl, toks, rng, temp, idx, drafts:
            self._verify_impl(params, kv, pt, sl, toks, rng, temp, idx,
                              drafts),
            donate_argnums=(1, 3, 4))
        self._prefill_cache: dict[int, Any] = {}
        # Slot-state patches run at ONE fixed shape (B+1 rows, trash-row
        # padded) through these jitted fns. Eager .at[idx].set() with a
        # dirty-count-sized idx compiled a fresh scatter per distinct count
        # — ~0.6s per eager compile on a tunneled chip, observed as 8-14s
        # TTFT stalls early in every serving run while counts 1,2,3,...
        # were each seen for the first time.
        self._patch_state = jax.jit(
            lambda pt, sl, temps, idx, ptv, slv, tv: (
                pt.at[idx].set(ptv), sl.at[idx].set(slv),
                temps.at[idx].set(tv)),
            donate_argnums=(0, 1, 2))
        self._patch_toks = jax.jit(
            lambda toks, idx, vals: toks.at[idx].set(vals),
            donate_argnums=(0,))
        self._zero_tok = None  # device int32(0), padding for override stacks

    # ---- tensor parallelism (ISSUE 20) ---------------------------------
    @staticmethod
    def tp_partition_rules():
        """Serve-side Megatron TP rules, consumed by
        parallel.sharding.rule_shardings (ordered; first re.search match
        wins). Column-parallel qkv/gate/up, row-parallel wo/w_down (their
        contractions psum across the axis), vocab-sharded lm_head (argmax
        composes exactly across shards), everything else — embed, norms,
        scalars — replicated. The attention split rides the kv-major GQA
        head order: H/tp query heads are exactly (Hkv/tp) whole kv-head
        groups, so per-head attention math never crosses a shard."""
        from jax.sharding import PartitionSpec as P
        return (
            (r"layers/attn/w[qkv]$", P(None, None, "tensor", None)),
            (r"layers/attn/wo$", P(None, "tensor", None, None)),
            (r"layers/mlp/w_(gate|up)$", P(None, None, "tensor")),
            (r"layers/mlp/w_down$", P(None, "tensor", None)),
            (r"lm_head$", P(None, "tensor")),
            (r".*", P()),
        )

    def _setup_tp_mesh(self):
        """Build the tp_degree-device "tensor" mesh and commit the engine's
        device state to it: params via the partition rules, the KV pool
        split per-KV-head (axis 1 of [L, Hkv, P, page, D]). Committed
        (device_put) shardings are what make every later jit — decode /
        verify / prefill / tier-inject — compile as a partitioned program
        without per-call annotations; donation then keeps the buffers
        sharded in place across steps. Small host-born operands (token
        patches, restore blobs) stay uncommitted and are resharded by the
        compiled programs' input layouts."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.parallel import sharding as shd
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh

        tp = self._tp
        mc = self.model_cfg
        for name, val in (("n_kv_heads", mc.n_kv_heads),
                          ("n_heads", mc.n_heads),
                          ("ffn_dim", mc.ffn_dim),
                          ("vocab_size", mc.vocab_size)):
            if val % tp:
                raise ValueError(
                    f"tp_degree={tp} must divide model {name}={val}")
        devices = jax.devices()
        if len(devices) < tp:
            raise ValueError(
                f"tp_degree={tp} needs {tp} devices, have {len(devices)}")
        mesh = build_mesh(MeshSpec(tensor=tp), devices[:tp])
        self.params = jax.device_put(
            self.params,
            shd.rule_shardings(self.tp_partition_rules(), self.params,
                               mesh))
        self.kv = jax.device_put(
            self.kv, NamedSharding(mesh, P(None, "tensor")))
        logger.info("TP mesh up: %s over %d devices (pool %d kv heads"
                    " -> %d per shard)", dict(mesh.shape), tp,
                    mc.n_kv_heads, mc.n_kv_heads // tp)
        return mesh

    # ---- compiled impls ------------------------------------------------
    def _decode_impl(self, params, kv, pt_full, sl_full, toks_full, rng,
                     temps_full, idx, num_steps: int = 1):
        """num_steps fused decode iterations in ONE program (lax.scan), at
        the PACKED width ``len(idx)``.

        On a tunneled chip each host->device dispatch costs a round trip;
        fusing K steps amortizes it to RTT/K per token (the standard TPU
        serving shape — cf. multi-step decode in TPU LLM stacks). ``idx``
        selects the active slots (padded with the trash row); the gather /
        scatter of the [W]-sized state stays on device. Returns all K
        sampled tokens [K, W] plus the full-size carried state."""
        jax = self._jax
        jnp = self._jnp
        pt = pt_full[idx]
        lens0 = sl_full[idx]
        toks0 = toks_full[idx]
        temps = temps_full[idx]

        def one(carry, _):
            kv_c, lens, toks, key = carry
            key, sub = jax.random.split(key)
            logits, kv_c, lens = self._kvc.paged_decode_step(
                params, kv_c, pt, lens, toks, self.model_cfg,
                self.cfg.page_size, self._attn_backend, mesh=self._mesh)
            toks = self._kvc.sample_tokens(
                logits, sub, temps, self.cfg.top_k)
            return (kv_c, lens, toks, key), toks

        (kv, new_lens, last, rng), all_toks = jax.lax.scan(
            one, (kv, lens0, toks0, rng), None, length=num_steps)
        # padding lanes must not accumulate garbage into the trash row
        # (its seq_len would creep toward int32 overflow on a long-lived
        # engine): pin it back to zero on scatter
        trash = self.cfg.max_batch_size
        sl_full = sl_full.at[idx].set(jnp.where(idx == trash, 0, new_lens))
        toks_full = toks_full.at[idx].set(last)
        return all_toks, toks_full, kv, sl_full, rng

    def _verify_impl(self, params, kv, pt_full, sl_full, toks_full, rng,
                     temps_full, idx, drafts):
        """Verify-k program (speculative decoding): k+1 token positions
        per slot — the current token followed by its k drafted tokens —
        scored in ONE fused multi-position pass (paged_verify_step) at the
        packed width W. logits[t] match what sequential decode would
        compute after consuming the first t draft tokens, so with greedy
        sampling output s[t] is bit-identical to baseline decode: the host
        accepts the longest prefix with drafts[t] == s[t] and emits
        s[:a+1] — one guaranteed token (s[0]) plus up to k free ones. The
        per-layer paged-cache read happens once per ROUND instead of once
        per token, which is the speedup (decode is memory-bound).

        Rejected tail positions wrote junk KV past the accepted length;
        the host rolls seq_lens back (dirty-slot patch), and because
        decode positions are always >= the prompt length those writes land
        in the slot's own suffix pages — never in shared prefix-cache
        pages — and are overwritten before any later step can attend to
        them. drafts: [W, k] int32 (-1 pads lanes/short drafts; -1 never
        equals a sampled token so padding can't be accepted, and junk
        from padded positions is causally invisible to earlier positions).
        Sampling uses one rng split for all positions — only greedy slots
        are ever drafted (_propose_locked), where sampling is argmax.
        Returns all samples [k+1, W] plus the carried full-size state."""
        jax = self._jax
        jnp = self._jnp
        pt = pt_full[idx]
        lens0 = sl_full[idx]
        temps = temps_full[idx]
        tokens = jnp.concatenate(
            [toks_full[idx][:, None], drafts.astype(jnp.int32)], axis=1)
        rng, sub = jax.random.split(rng)
        logits, kv, new_lens = self._kvc.paged_verify_step(
            params, kv, pt, lens0, tokens, self.model_cfg,
            self.cfg.page_size, self._attn_backend, mesh=self._mesh)
        t = tokens.shape[1]
        out = self._kvc.sample_tokens(
            logits.reshape(-1, logits.shape[-1]), sub,
            jnp.repeat(temps, t), self.cfg.top_k).reshape(-1, t)
        all_toks = jnp.swapaxes(out, 0, 1)                  # [k+1, W]
        # scattered lens are k+1 past the truth for every rejected draft;
        # the harvest marks every participating slot dirty with the
        # rolled-back length, so this value is never read by a later
        # dispatch. Trash row pinned to zero as in _decode_impl.
        trash = self.cfg.max_batch_size
        sl_full = sl_full.at[idx].set(jnp.where(idx == trash, 0, new_lens))
        toks_full = toks_full.at[idx].set(all_toks[-1])
        return all_toks, toks_full, kv, sl_full, rng

    def _prefill_fn(self, bucket: int):
        """Prefill + first-token sampling fused in ONE jitted program.

        Sampling on device keeps admission fully asynchronous: the engine
        loop never blocks on a host round trip per request (the old
        ``int(tok[0])`` sync serialized ~1 RTT per admission — the dominant
        cost of the serving stack on a tunneled chip). The sampled token is
        returned as a device scalar; the harvest pipeline records it.

        top_k is the ENGINE's (static — per-request values would compile a
        new program per distinct k, each stalling the loop; decode already
        uses the engine setting, see submit())."""
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            jax = self._jax
            top_k = self.cfg.top_k

            def impl(params, kv, page_table, tokens, true_len, rng, temp):
                logits, kv = self._kvc.paged_prefill(
                    params, kv, page_table, tokens, true_len,
                    self.model_cfg, self.cfg.page_size)
                tok = self._kvc.sample_tokens(
                    logits[None, :], rng, temp, top_k)
                return tok[0], kv

            fn = jax.jit(impl, donate_argnums=(1,))
            self._prefill_cache[bucket] = fn
        return fn

    def _chunk_fn(self, clen: int):
        """Chunked-prefill program for a chunk of ``clen`` tokens: write the
        chunk's KV through the page pool, attend over everything cached so
        far, and sample a (candidate) next token on device — only the final
        chunk's sample is used. One program per chunk bucket (full chunks
        share one shape; the padded tail adds at most log2(prefill_chunk))."""
        key = ("chunk", clen)
        fn = self._prefill_cache.get(key)
        if fn is None:
            jax = self._jax
            top_k = self.cfg.top_k

            def impl(params, kv, page_table, tokens, start, true_len, rng,
                     temp):
                logits, kv = self._kvc.paged_prefill_chunk(
                    params, kv, page_table, tokens, start, true_len,
                    self.model_cfg, self.cfg.page_size,
                    self._attn_backend, mesh=self._mesh)
                tok = self._kvc.sample_tokens(
                    logits[None, :], rng, temp, top_k)
                return tok[0], kv

            fn = jax.jit(impl, donate_argnums=(1,))
            self._prefill_cache[key] = fn
        return fn

    # ---- public API ----------------------------------------------------
    def start(self):
        if self._loop_thread is None:
            if self.cfg.warmup_compile:
                self._warmup_decode_programs()
            self._loop_thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True)
            self._loop_thread.start()

    def _warmup_decode_programs(self):
        """Compile every (bucket width, block length) decode program before
        serving: a first-use compile mid-traffic stalls ALL active
        generations for the whole XLA compile (tens of seconds on a
        tunneled chip) and wrecks tail latency. All-trash index vectors
        make the warmup dispatches write only into the trash page."""
        jnp = self._jnp
        trash = self.cfg.max_batch_size
        # derive from _bucket_width so the warmed set can never diverge
        # from the widths _step actually dispatches
        widths = sorted({self._bucket_width(n)
                         for n in range(1, self.cfg.max_batch_size + 1)})
        toks = self._dev_tokens
        if toks is None:
            toks = jnp.zeros((self.cfg.max_batch_size + 1,), jnp.int32)
        tiers = {1, max(1, min(self.cfg.pressure_decode_block,
                               self.cfg.decode_block)),
                 self.cfg.decode_block}
        if self._spec_on:
            # the spec-capped idle tier (_select_block) dispatches too
            tiers.add(min(self.cfg.decode_block,
                          max(1, self.cfg.spec_draft_len)))
        for w in widths:
            idx = jnp.full((w,), trash, jnp.int32)
            for k in tiers:
                # compile_scope registers each (width, block) signature so
                # the traffic-path scopes see it as already compiled; a
                # warmup compile is by definition not mid-traffic
                with self._prof.compile_scope("decode", ("decode", w, k)):
                    _all, toks, self.kv, self._sl_dev, self._rng = \
                        self._decode(
                            self.params, self.kv, self._pt_dev,
                            self._sl_dev, toks, self._rng,
                            self._temps_dev, idx, k)
            if self._spec_on:
                # the verify-k program per width too: an uncompiled verify
                # stalls the first speculative round mid-traffic exactly
                # like an uncompiled decode block would
                drafts = jnp.full((w, self.cfg.spec_draft_len), -1,
                                  jnp.int32)
                with self._prof.compile_scope(
                        "verify", ("verify", w, self.cfg.spec_draft_len)):
                    _all, toks, self.kv, self._sl_dev, self._rng = \
                        self._verify(
                            self.params, self.kv, self._pt_dev,
                            self._sl_dev, toks, self._rng,
                            self._temps_dev, idx, drafts)
        # the fixed-shape slot patches (all-trash write of zeros is a no-op)
        didx = jnp.full((trash + 1,), trash, jnp.int32)
        self._pt_dev, self._sl_dev, self._temps_dev = self._patch_state(
            self._pt_dev, self._sl_dev, self._temps_dev, didx,
            jnp.zeros((trash + 1, self.max_pages_per_seq), jnp.int32),
            jnp.zeros((trash + 1,), jnp.int32),
            jnp.zeros((trash + 1,), jnp.float32))
        if self._zero_tok is None:
            self._zero_tok = jnp.int32(0)
        toks = self._patch_toks(
            toks, didx, jnp.stack([self._zero_tok] * (trash + 1)))
        if self._kv_tier_on:
            # the tier-restore scatter too: its one fixed shape would
            # otherwise compile on the first tier hit, mid-traffic (an
            # all-trash-page write of zeros is a no-op)
            mp = self.max_pages_per_seq
            zb = jnp.zeros(self.kv["k"].shape[:2] + (mp,)
                           + self.kv["k"].shape[3:], self.kv["k"].dtype)
            with self._prof.compile_scope("kv_tier_inject",
                                          ("kv_tier_inject", mp)):
                self.kv = self._tier_inject(
                    self.kv, zb, zb, jnp.zeros((mp,), jnp.int32))
        self._dev_tokens = toks
        self._jax.block_until_ready(toks)

    def shutdown(self):
        self._stop.set()
        self._wake.set()
        loop_alive = False
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
            loop_alive = self._loop_thread.is_alive()
            self._loop_thread = None
        # surface already-computed completions: the loop may exit with
        # dispatched blocks still unharvested, and their waiters would
        # otherwise time out on results that exist. Skip if the loop thread
        # is wedged past the join timeout — draining concurrently with it
        # would race on _pending.
        if loop_alive:
            return
        try:
            while self._pending:
                self._harvest_one()
        except Exception:  # noqa: BLE001 - device may already be gone
            self._pending.clear()
        # restore streams have their own worker threads; cut them before
        # the tier closes underneath them
        with self._lock:
            restoring = list(self._restoring)
        for req in restoring:
            if req.restore_stream is not None:
                req.restore_stream.abort()
                req.restore_stream = None
        if self._kv_tier is not None:
            # flush captured spills, then drop the tier's blobs and
            # retract our cluster-index entries — a clean shutdown must
            # not leave the index pointing at refs nobody serves
            try:
                self._kv_tier_flush()
            except Exception:  # noqa: BLE001
                self._tier_pending.clear()
            self._kv_tier.close()

    def submit(self, prompt: str | list[int], *,
               max_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               request_id: Optional[str] = None,
               prefix_digests: Optional[list] = None,
               resume_tokens: Optional[list] = None,
               disagg: bool = False) -> str:
        """Enqueue a request; returns its id. Tokens stream via drain().

        ``resume_tokens`` is a mid-stream failover continuation (ISSUE
        14): the token ids a dead replica already generated for this
        request. They extend the admission sequence past the prompt —
        the cache-aware admission path (local prefix match, kv-tier
        restore, suffix-only chunked prefill) then recovers or
        recomputes the dead replica's KV and decode resumes at the
        exact next token; drain() emits ONLY post-resume tokens.
        ``max_tokens`` for a continuation is the REMAINING budget
        (original minus the tokens already emitted)."""
        if isinstance(prompt, str):
            toks = self.tokenizer.encode(prompt)
        else:
            toks = list(prompt)
        # the prompt cap applies BEFORE the continuation is appended:
        # the original leg was capped the same way, so the digest chain
        # over the prompt pages is identical across legs
        toks = toks[: self.cfg.max_prompt_len]
        resume_len = 0
        if resume_tokens:
            if not self.cfg.failover_enabled:
                raise ValueError(
                    "continuation submit with failover_enabled=False")
            # leave >=1 position of generation room: a continuation that
            # would fill max_seq_len exactly still has to sample the next
            # token to make progress (the tail is truncated, which only
            # loses speculative room, never emitted tokens)
            resume = list(resume_tokens)[: max(
                0, self.cfg.max_seq_len - 1 - len(toks))]
            resume_len = len(resume)
            toks = toks + [int(t) for t in resume]
        req = _Request(
            request_id=request_id or uuid.uuid4().hex[:16],
            prompt_tokens=toks,
            max_tokens=max(1, min(max_tokens or self.cfg.max_tokens,
                                  self.cfg.max_seq_len - len(toks))),
            temperature=(self.cfg.temperature if temperature is None
                         else temperature),
            top_k=self.cfg.top_k if top_k is None else top_k,
            stop_token=getattr(self.tokenizer, "eos_token_id", None),
            ingress_digests=(list(prefix_digests)
                             if prefix_digests else None),
            resume_len=resume_len,
            disagg=bool(disagg))
        from ray_tpu.core import deadline as request_deadline
        from ray_tpu.observability import tracing
        req.trace_ctx = tracing.inject()
        req.deadline = request_deadline.current()
        if req.top_k != self.cfg.top_k:
            # All sampling (prefill first token + fused decode) uses the
            # ENGINE's top_k: k is static to the compiled programs, and a
            # per-request k would compile (and loop-stall on) a new program
            # per distinct value.
            logger.warning(
                "request top_k=%s differs from engine top_k=%s; sampling "
                "uses the engine setting", req.top_k, self.cfg.top_k)
        with self._lock:
            self._requests[req.request_id] = req
            self._waiting.append(req)
            self.stats["requests"] += 1
            if resume_len:
                self.stats["failover_resumed"] += 1
        if resume_len:
            # a failed replica's stream is being spliced onto this one —
            # journal it under the same request id so the postmortem
            # timeline joins it against the chaos fault that caused it
            _fr.emit("failover_resume", "WARNING",
                     request_id=req.request_id,
                     attrs={"resume_len": int(resume_len),
                            "model": str(self.cfg.model_id)})
        self._wake.set()
        return req.request_id

    def cancel(self, request_id: str) -> None:
        """Abandon a request (client disconnected mid-stream): a waiting
        request is dropped immediately; a slotted one finishes at its next
        recorded token (the loop then frees its slot/pages on the normal
        completion path). The entry is removed so nothing leaks when no
        one drains it again."""
        with self._lock:
            req = self._requests.pop(request_id, None)
            if req is None:
                return
            if req in self._waiting:
                self._waiting.remove(req)
                req.done = True
                req.finished_at = time.monotonic()
                # a concurrent result() waiter is parked on this event; a
                # dropped WAITING request must release it immediately, not
                # leave it blocking to its full timeout
                req.done_event.set()
                return
            if req in self._prefilling or req in self._restoring:
                # mid chunked prefill (or mid tier-restore stream): flag
                # it and let the LOOP free the slot/pages
                # (_abort_prefilling) — the loop may be building a chunk
                # dispatch from req.pages on the host right now, so
                # freeing here could hand those pages to a later admission
                # while this one still writes them. Without this branch the
                # request would chunk-prefill its ENTIRE remaining prompt,
                # decode a token, and only then free — the _prefilling
                # cancel leak.
                req.prefill_cancelled = True
                req.abandoned = True
                self._requests[request_id] = req  # loop reaps on abort
                self._wake.set()
                return
            if not req.done:
                # finish at next token; keep a tracking entry so the loop's
                # completion path still finds consistent state, and flag it
                # abandoned so completion also reaps the entry (no drain
                # will ever come to do it)
                req.max_tokens = max(1, len(req.generated))
                req.abandoned = True
                self._requests[request_id] = req
                req.drained_upto = len(req.generated)
        self._wake.set()

    def drain(self, request_id: str) -> dict:
        """New tokens since the last drain + done flag (streaming poll)."""
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                return {"tokens": [], "text": "", "done": True,
                        "error": "unknown request"}
            new = req.generated[req.drained_upto:]
            req.drained_upto = len(req.generated)
            done = req.done
            err = req.error
            if done and req.drained_upto >= len(req.generated):
                # fully drained: allow GC
                self._requests.pop(request_id, None)
        out = {"tokens": new, "text": self.tokenizer.decode(new),
               "done": done, "error": err}
        if done:
            # final chunk carries the per-request attribution (queue wait +
            # engine stage timeline) so the streaming path surfaces the
            # same critical-path record as result(). Built OUTSIDE the
            # lock — pure host computation, but no reason to hold it.
            out.update(self._attribution_payload(req))
        return out

    def result(self, request_id: str, timeout: Optional[float] = None) -> dict:
        """Block until the request completes; returns the full completion.

        The wait is bounded by min(timeout, remaining request deadline);
        with neither, the 120 s guard still applies (a hung engine must not
        pin the caller forever). On expiry the request is CANCELLED — its
        slot/pages free at the next recorded token instead of decoding to
        max_tokens for nobody."""
        from ray_tpu.core import deadline as request_deadline
        if timeout is None:
            timeout = 120.0
        timeout = request_deadline.bound(timeout)
        with self._lock:
            req = self._requests.get(request_id)
        if req is None:
            return {"text": "", "tokens": [], "error": "unknown request"}
        if not req.done_event.wait(timeout):
            self.cancel(request_id)
            expired = (req.deadline is not None
                       and time.time() >= req.deadline)
            return {"text": "", "tokens": [],
                    "error": "deadline exceeded" if expired else "timeout"}
        with self._lock:
            self._requests.pop(request_id, None)
        ttft = (req.first_token_at - req.submitted_at
                if req.first_token_at else None)
        gaps = sorted(req.itl_gaps)
        out = {
            "text": self.tokenizer.decode(req.generated),
            "tokens": list(req.generated),
            "num_prompt_tokens": len(req.prompt_tokens),
            "num_generated_tokens": len(req.generated),
            "error": req.error,
            "ttft_s": ttft,
            # median inter-token gap at host record time (None for 0/1
            # token completions); bursty under pipelined harvests — see
            # _Request.itl_gaps
            "itl_s": gaps[len(gaps) // 2] if gaps else None,
            "latency_s": (req.finished_at or time.monotonic())
            - req.submitted_at,
        }
        out.update(self._attribution_payload(req))
        return out

    def _attribution_payload(self, req: _Request) -> dict:
        """Per-request critical-path extras (ISSUE 12): queue wait plus
        the engine-side stage timeline, carried in the response metadata
        back to the proxy (different process — stamps can't ride a
        contextvar across the wire)."""
        from ray_tpu.observability import attribution
        gaps = sorted(req.itl_gaps)
        queue_wait = ((req.admitted_at - req.submitted_at)
                      if req.admitted_at is not None else None)
        return {
            "request_id": req.request_id,
            "queue_wait_s": queue_wait,
            "stages": attribution.engine_stages(
                submitted_wall=req.submitted_wall,
                submitted_at=req.submitted_at,
                admitted_at=req.admitted_at,
                first_token_at=req.first_token_at,
                finished_at=req.finished_at,
                cached_tokens=req.cached_tokens,
                restored_tokens=req.restored_tokens,
                restore_bytes=req.restore_bytes,
                restore_ms=req.restore_ms,
                restore_wire_bytes=req.restore_wire_bytes,
                restore_decode_ms=req.restore_decode_ms,
                restore_overlap_ms=req.restore_overlap_ms,
                restore_partial=req.restore_partial,
                prompt_tokens=len(req.prompt_tokens),
                generated_tokens=len(req.generated),
                itl_s=gaps[len(gaps) // 2] if gaps else None),
        }

    def generate(self, prompt: str, **kw) -> dict:
        """Convenience: submit + wait."""
        rid = self.submit(prompt, **kw)
        return self.result(rid)

    def request_progress(self, request_id: str) -> Optional[dict]:
        """Per-request failover journal (ISSUE 14): the progress a
        resume needs — accepted token ids, how much of a continuation's
        prior work was recovered from cache/tier, and the restore cost
        (stamped into the proxy's ``failover`` attribution stage)."""
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                return None
            return {"prompt_tokens": len(req.prompt_tokens),
                    "generated": list(req.generated),
                    "resume_len": req.resume_len,
                    "cached_tokens": req.cached_tokens,
                    "restored_tokens": req.restored_tokens,
                    "restore_bytes": req.restore_bytes,
                    "restore_ms": req.restore_ms,
                    "admitted": req.admitted_at is not None}

    def prefix_summary(self, max_pages: Optional[int] = None):
        """(index_version, resident page-chain digest hex list) for the
        affinity router, or None when prefix caching is off (the caller
        marks this engine unsupported and stops probing)."""
        if not self._prefix_cache_on:
            return None
        cap = (self.cfg.prefix_summary_max_pages if max_pages is None
               else max_pages)
        return self.allocator.prefix_summary(cap)

    def prefetch_hint(self, digests: list[str]) -> dict:
        """Router affinity-miss hint: start pulling the tier-held tail of
        this chain NOW so the restore inside _admit finds the pages in the
        hint buffer instead of paying the remote fetch inline. Locally
        resident pages are skipped; everything is best-effort."""
        if not self._kv_tier_on or not digests:
            return {"accepted": False}
        start = self.allocator.match_digest_chain(list(digests))
        if start >= len(digests):
            return {"accepted": False}
        return {"accepted": self._kv_tier.prefetch(list(digests), start)}

    def engine_stats(self) -> dict:
        with self._lock:
            active = sum(1 for r in self.slot_req if r is not None)
            waiting = len(self._waiting)
            prefilling = len(self._prefilling)
            restoring = len(self._restoring)
        # mid-chunked-prefill and mid-restore-stream requests hold a slot
        # + pages but are not yet in slot_req: load monitoring must see
        # them (as waiting) or autoscaling under-counts
        free = self.allocator.available()
        out = {**self.stats, "active_slots": active,
               "waiting": waiting + prefilling + restoring,
               "prefilling": prefilling, "restoring": restoring,
               "free_pages": free,
               # gauges: the decode-block tier actually dispatched last
               # (1 / pressure_decode_block / decode_block — admission
               # pressure made visible) and the live dispatched-but-
               # unharvested block count (vs cfg.pipeline_depth)
               "decode_block_effective": self._last_block,
               "pending_pipeline_depth": len(self._pending)}
        # introspection (observability/profiling.py): per-phase p50/p95 +
        # itl_s (None until sampled / while profiling_enabled=False),
        # compile-event counters (always live), device-memory gauges.
        # compile_s is the profiler's measured total — the stats-dict slot
        # predates the tracker and is overridden here.
        out.update(self._prof.phase_stats())
        out["compile_events"] = self._prof.compile_events
        out["mid_traffic_compiles"] = self._prof.mid_traffic_compiles
        out["compile_s"] = round(self._prof.compile_s, 3)
        # paged-attention backend surface (ISSUE 18): which kernel family
        # this replica compiled in (string + a numeric twin exporters can
        # gauge), plus how many attention-bearing programs — decode /
        # verify / chunk tiers — have been compiled so far. The dispatch
        # counters live in self.stats above.
        out["attention_backend"] = self._attn_backend
        out["attn_backend_pallas"] = int(self._attn_backend == "pallas")
        out["attn_kernel_compiles"] = self._prof.compile_count(
            ("decode", "verify", "chunk"))
        # tensor-parallel surface (ISSUE 20), stable-key contract: degree
        # + mesh shape (string — exporters one-hot it like
        # attention_backend) are always emitted ("none"/1 single-chip),
        # and the byte gauges give ONE chip's slice of the pool — page
        # counts everywhere else stay whole-replica logical pages (see
        # PageAllocator), so dashboards sizing a chip's HBM read these
        # two instead of dividing counts themselves.
        out["tp_degree"] = self._tp
        # only live axes: build_mesh materializes every canonical axis at
        # size 1, which is noise in a gauge tag
        out["mesh_shape"] = ("none" if self._mesh is None else ",".join(
            f"{a}={n}" for a, n in dict(self._mesh.shape).items()
            if n > 1))
        pool_bytes = int(self.kv["k"].nbytes + self.kv["v"].nbytes)
        out["kv_shard_pool_bytes"] = pool_bytes // self._tp
        out["kv_shard_page_occupancy"] = (
            (self.cfg.num_pages - free) * pool_bytes
            // (self.cfg.num_pages * self._tp))
        out.update(self._prof.memory_stats(
            used_pages=self.cfg.num_pages - free,
            total_pages=self.cfg.num_pages))
        if self._spec_on:
            d = self.stats["spec_drafted_tokens"]
            out["spec_accept_rate"] = (
                round(self.stats["spec_accepted_tokens"] / d, 4) if d
                else 0.0)
        if self._prefix_cache_on:
            cs = self.allocator.cache_stats()
            out.update({"prefix_cached_pages": cs["cached_pages"],
                        "prefix_evictable_pages": cs["evictable_pages"],
                        "prefix_shared_pages": cs["shared_pages"],
                        "prefix_evictions": cs["evicted"],
                        "prefix_hit_pages": cs["hit_pages"],
                        "prefix_inserted_pages": cs["inserted"]})
        # tier byte gauges are always emitted (0 when the tier is off) so
        # exporters and the README drift guard see a stable key set; the
        # spill/restore counters live in self.stats above
        ts = self._kv_tier.stats() if self._kv_tier is not None else {}
        out["tier_bytes_shm"] = ts.get("shm_bytes", 0)
        out["tier_bytes_disk"] = ts.get("disk_bytes", 0)
        # page codec (ISSUE 15): raw-byte twins of the tier gauges plus
        # the cumulative ratio (= capacity multiplier on both byte caps)
        # and the per-page codec cost medians
        out["tier_bytes_shm_raw"] = ts.get("shm_bytes_raw", 0)
        out["tier_bytes_disk_raw"] = ts.get("disk_bytes_raw", 0)
        out["tier_codec_ratio"] = ts.get("codec_ratio", 0.0)
        out["tier_encode_ms_p50"] = ts.get("encode_ms_p50", 0.0)
        out["tier_decode_ms_p50"] = ts.get("decode_ms_p50", 0.0)
        # affinity-routing surface (ISSUE 10), same stable-key contract:
        # summary export state + hinted-prefetch effectiveness
        out["tier_prefetch_hints"] = ts.get("prefetch_hints", 0)
        out["tier_prefetch_pages"] = ts.get("prefetch_pages", 0)
        out["tier_prefetch_hit_pages"] = ts.get("prefetch_hit_pages", 0)
        if self._prefix_cache_on:
            ver, digs = self.allocator.prefix_summary(
                self.cfg.prefix_summary_max_pages)
            out["prefix_summary_version"] = ver
            out["prefix_summary_pages"] = len(digs)
        else:
            out["prefix_summary_version"] = 0
            out["prefix_summary_pages"] = 0
        return out

    # ---- engine loop ---------------------------------------------------
    def _loop(self):
        prof = self._prof
        while not self._stop.is_set():
            # admit timing covers the whole admission pass (including the
            # async prefill dispatches of short prompts, which are ALSO
            # sampled individually as "prefill"); idle passes that admit
            # nothing are not recorded — the ring holds work, not waiting
            if prof.enabled:
                t0 = time.perf_counter()
                if self._admit():
                    prof.record("admit", time.perf_counter() - t0)
            else:
                self._admit()
            # streaming tier restores first: a chunk that landed since
            # the last pass injects before this pass's prefill chunks
            # dispatch, and a stream that just finished routes its
            # request into _prefilling in time for THIS pass
            restored = self._restore_steps() if self._kv_tier_on else 0
            chunks = self._prefill_chunks()
            if self._spill_req is not None:
                # drain-time eager spill (ISSUE 14): gather + flush on
                # THIS thread, then release the waiter — its return must
                # mean the chains are actually in the tier
                ev, box = self._spill_req
                self._spill_req = None
                try:
                    box.append(self._spill_inflight_now())
                    self._kv_tier_flush()
                finally:
                    ev.set()
            if self._warm_req is not None:
                # cache-warm scale-up (ISSUE 17): restore the fleet's
                # hottest tier chains into the local prefix cache on THIS
                # thread — the replica is pre-routing-table, so the loop
                # has no traffic to stall
                ev, box, w_mb, w_bs = self._warm_req
                self._warm_req = None
                try:
                    box.append(self._warm_start_now(w_mb, w_bs))
                finally:
                    ev.set()
            # chunk dispatches count as progress: an otherwise-idle engine
            # mid-chunked-prefill must not sleep between chunks. Restore
            # progress counts too; a stream WAITING on fetches does not —
            # the idle wait below parks on _wake, which the stream's
            # on_ready sets the moment new pages land
            dispatched = self._step() or chunks > 0 or restored > 0
            if self._kv_tier_on:
                # spill gathers captured by evictions this pass: their
                # device->host copies were started at dispatch, so this
                # is mostly bookkeeping + an object-store put
                self._kv_tier_flush()
            # Eager harvest: pop every block whose device result already
            # landed (is_ready) — holding computed tokens unharvested just
            # adds their age to TTFT/ITL. The blocking PIPELINE_DEPTH trim
            # in _step still bounds the queue when results are slow. On
            # jax without a readiness API (probed once at init), fall back
            # to a BOUNDED harvest: pop the oldest block while at least
            # one newer block is dispatched behind it — the wait is
            # bounded by work the device is already retiring, and one
            # block stays in flight so the device never idles.
            while self._pending and (
                    self._ready(self._pending[0][0])
                    or (not self._is_ready_supported
                        and len(self._pending) > 1)):
                self._harvest_one()
            if not dispatched:
                if self._pending:
                    self._harvest_one()  # drain the pipeline tail
                    continue
                self._wake.wait(timeout=0.05)
                self._wake.clear()

    def _ready(self, dev_arr) -> bool:
        if not self._is_ready_supported:
            return False
        try:
            return dev_arr.is_ready()
        except AttributeError:  # probe object mismatch; be conservative
            return False

    @staticmethod
    def _start_fetch(dev_arr) -> None:
        """Kick off the device->host copy at DISPATCH time so the later
        harvest finds the bytes already local. Through a tunneled chip a
        blocking fetch costs ~250ms of host latency per block — serialized
        per harvest, it (not device execution) was the throughput and TTFT
        bound."""
        try:
            dev_arr.copy_to_host_async()
        except AttributeError:
            pass

    def _bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.cfg.max_prompt_len)

    def _admissions_blocked(self) -> bool:
        """Requests waiting while slots are free (= page-pool starved), or
        a chunked prefill mid-flight: shrink decode blocks so page
        reclamation isn't a whole block late and prefill chunks interleave
        tightly. Lock held. Subclasses with extra admission queues extend
        this."""
        return (bool(self._waiting) and bool(self.free_slots)) \
            or bool(self._prefilling) or bool(self._restoring)

    def _bucket_width(self, n: int) -> int:
        """Packed decode width: smallest power-of-two ≥ n (floor 4), capped
        at max_batch_size — a handful of compiled widths total."""
        w = 4
        while w < n:
            w *= 2
        return min(w, self.cfg.max_batch_size)

    def _shed_expired_waiting(self) -> None:
        """Drop WAITING requests whose deadline passed: no slot, no pages,
        no prefill — the caller stopped listening ("The Tail at Scale").
        Slotted requests are not preempted; cancel() handles those."""
        now = time.time()
        shed: list[_Request] = []
        with self._lock:
            keep = []
            for req in self._waiting:
                if req.deadline is not None and now >= req.deadline:
                    shed.append(req)
                else:
                    keep.append(req)
            if shed:
                self._waiting = keep
                self.stats["shed_expired"] += len(shed)
                for req in shed:
                    req.error = "deadline exceeded"
                    req.done = True
                    req.finished_at = time.monotonic()
        for req in shed:
            req.done_event.set()

    def _admit(self) -> int:
        """Move waiting requests into free slots (prefill each)."""
        self._shed_expired_waiting()
        admitted = 0
        while True:
            with self._lock:
                if not self._waiting or not self.free_slots:
                    return admitted
                req = self._waiting[0]
                # cache-aware admission: longest indexed full-page prefix
                # (increffed — shared pages go into this slot's page table
                # and only the suffix gets prefilled). match_prefix caps
                # the match so at least one suffix token remains: the
                # suffix pass is what produces the first sampled token.
                matched: list[int] = []
                if self._prefix_cache_on:
                    matched = self.allocator.match_prefix(
                        req.prompt_tokens, self.cfg.page_size)
                n_pages = -(-max(len(req.prompt_tokens) + req.max_tokens, 1)
                            // self.cfg.page_size)
                n_pages = min(n_pages, self.max_pages_per_seq)
                pages = self.allocator.alloc(n_pages - len(matched))
                if pages is None:
                    # page pool exhausted; drop the match refs (pages park
                    # back in the cached LRU, still matchable) + retry next
                    # loop
                    if matched:
                        self.allocator.free(matched)
                    return admitted
                self._waiting.pop(0)
                slot = self.free_slots.pop()
                req.slot = slot
                req.admitted_at = time.monotonic()
                req.pages = matched + pages
                req.cached_tokens = len(matched) * self.cfg.page_size
                req.prefill_pos = req.cached_tokens
                if self._prefix_cache_on \
                        and len(req.prompt_tokens) > self.cfg.page_size:
                    key = "prefix_hits" if matched else "prefix_misses"
                    self.stats[key] += 1
                    self.stats["prefix_hit_tokens"] += req.cached_tokens
            # queue-wait phase sample (submit→admit), recorded OUTSIDE the
            # lock: the profiler observes a metrics histogram, which must
            # never run under the engine lock (graftlint lock-discipline)
            self._prof.record("queue_wait",
                              req.admitted_at - req.submitted_at)
            if self._kv_tier_on and self._kv_tier_begin_restore(
                    req, len(matched)):
                # pipelined streaming restore (ISSUE 15): the stream's
                # worker plans sources (local walk + ONE CP match) and
                # fetches chunk-by-chunk off this thread; the loop's
                # _restore_steps decodes+injects chunks as they land and
                # routes the request on to its suffix prefill when the
                # stream ends. Admission never blocks on tier I/O — a
                # dead peer stalls ONE chunk of ONE request (per-chunk
                # budget), and everything landed before the stall is
                # kept (partial restore), never a whole-chain miss.
                with self._lock:
                    self._restoring.append(req)
                admitted += 1
                continue
            if req.resume_len:
                # tokens of the dead replica's work recovered WITHOUT
                # recompute (local prefix pages; the tier-restore leg
                # accounts its share when its stream finalizes)
                self.stats["failover_restored_tokens"] += req.cached_tokens
            self._route_admitted(req)
            admitted += 1

    def _route_admitted(self, req: _Request) -> None:
        """Send an admitted request (prefix matched, tier restore — if
        any — finished) to its prompt pass."""
        suffix = len(req.prompt_tokens) - req.prefill_pos
        if req.prefill_pos > 0 or (self.cfg.prefill_chunk > 0
                                   and suffix > self.cfg.prefill_chunk):
            # long prompt OR cached prefix: prefill the (remaining)
            # suffix in chunks interleaved with decode blocks (the loop
            # drives _prefill_chunks). A cached prefix MUST go through
            # the chunk program — paged_prefill writes from position 0
            # and would scribble on the shared pages; the chunk pass
            # starts at prefill_pos and reads the cached prefix back
            # through the page table.
            with self._lock:
                self._prefilling.append(req)
        else:
            self._prefill(req)

    # ---- tiered KV cache (kv_tier.py) ---------------------------------
    _SPILL_GATHER_WIDTH = 8  # fixed gather width: one compiled shape

    def _spill_capture(self, evicted) -> None:
        """Allocator spill hook: runs on the loop thread immediately
        after an evicting alloc()/free(), BEFORE the caller can dispatch
        writes that reuse the pages — so the gather dispatched here reads
        the pre-eviction KV on the ordered device stream. Only the
        dispatch happens here; the device->host copy is started async and
        harvested later by _kv_tier_flush, off the admission hot path."""
        jnp = self._jnp
        ents = [(p, d, pos) for (p, d, pos) in evicted if pos is not None]
        if not ents:
            return
        w = self._SPILL_GATHER_WIDTH
        for i in range(0, len(ents), w):
            batch = ents[i:i + w]
            # pad the gather index to the fixed width with the trash page
            # (sliced off host-side) so spill batches of every size share
            # one compiled gather
            pidx = jnp.asarray(
                [p for p, _, _ in batch] + [0] * (w - len(batch)),
                jnp.int32)
            bk = jnp.take(self.kv["k"], pidx, axis=2)
            bv = jnp.take(self.kv["v"], pidx, axis=2)
            self._start_fetch(bk)
            self._start_fetch(bv)
            self._tier_pending.append((bk, bv, batch))

    def _kv_tier_flush(self) -> None:
        """Harvest captured spill gathers (host copy already in flight)
        and hand them to the tier store. A failed put degrades to a
        plain eviction — the pages are long since back on the free
        list."""
        if not self._tier_pending:
            return
        pend, self._tier_pending = self._tier_pending, []
        for bk, bv, ents in pend:
            try:
                k_np = np.asarray(bk)[:, :, :len(ents)]
                v_np = np.asarray(bv)[:, :, :len(ents)]
                n = self._kv_tier.put(
                    k_np, v_np,
                    digests=[d.hex() for _, d, _ in ents],
                    tokens=[(pos + 1) * self.cfg.page_size
                            for _, _, pos in ents])
                self.stats["spilled_pages"] += n
            except Exception:  # noqa: BLE001 - spill is best-effort
                logger.warning("kv-tier spill put failed; chain evicted "
                               "without spilling", exc_info=True)

    def spill_inflight(self, timeout_s: float = 5.0) -> int:
        """Eagerly spill the computed full KV pages of every LIVE chain
        into the tier (ISSUE 14 drain/SIGTERM path). Ordinary spill
        waits for pool eviction; a draining or dying replica's in-flight
        requests would take their KV with them — this pushes the chains
        out NOW so a surviving replica can tier-restore a continuation
        instead of recomputing it. Thread-safe: the gather runs on the
        engine loop via a handshake (one driver per device stream), or
        directly when the loop is not running. Returns pages spilled."""
        if not self._kv_tier_on:
            return 0
        loop = self._loop_thread
        if loop is None or not loop.is_alive():
            n = self._spill_inflight_now()
            self._kv_tier_flush()
            return n
        ev = threading.Event()
        box: list = []
        self._spill_req = (ev, box)
        self._wake.set()
        ev.wait(timeout_s)
        return box[0] if box else 0

    def _spill_inflight_now(self) -> int:
        """Capture spill gathers for every live request's full pages
        (slotted or mid chunked prefill). Engine-loop thread only (or
        the caller's, when the loop is down) — the same thread also
        frees pages, so the entries can't go stale under us."""
        if self._kv_tier is None:
            return 0
        ps = self.cfg.page_size
        ents: list = []
        with self._lock:
            live = [r for r in self.slot_req if r is not None and not r.done]
            live += [r for r in self._prefilling
                     if not r.prefill_cancelled and not r.done]
            # mid-restore-stream requests hold pages too; their injected
            # frontier is prefill_pos, same as the chunked-prefill case
            live += [r for r in self._restoring
                     if not r.prefill_cancelled and not r.done]
            for req in live:
                toks = req.prompt_tokens + req.generated
                if req.dispatched > 0:
                    # armed slot: prompt KV fully written; a generated
                    # token's KV is written when it feeds the NEXT step,
                    # so the newest recorded token may not be cached yet
                    covered = len(req.prompt_tokens) + max(
                        0, len(req.generated) - 1)
                else:
                    covered = req.prefill_pos   # mid chunked prefill
                limit = min(covered // ps, len(req.pages))
                digest = b""
                for i in range(limit):
                    digest = self._kvc._chain_digest(
                        digest, toks[i * ps:(i + 1) * ps])
                    ents.append((req.pages[i], digest, i))
        if not ents:
            return 0
        self._spill_capture(ents)
        return len(ents)

    def warm_start(self, max_bytes: Optional[int] = None,
                   budget_s: Optional[float] = None) -> dict:
        """Pre-populate the prefix cache from the cluster tier BEFORE the
        first request (ISSUE 17 cache-warm scale-up): enumerate the
        fleet's restorable chains from the CP ``kv_tier:`` index
        (hottest first), stream them through ChainStream, inject the
        pages and register their digests — so the router's affinity
        scoring sees this replica as a warm holder from its very first
        summary. Bounded by a wire-byte budget AND a time budget; every
        failure degrades to a smaller (or empty) warm set. Thread-safe
        via the same loop handshake as spill_inflight. Returns
        {"supported", "pages", "chains", "wire_bytes", "ms"}."""
        out = {"supported": False, "pages": 0, "chains": 0,
               "wire_bytes": 0, "ms": 0.0}
        if not self._kv_tier_on or not self.cfg.warm_start_enabled:
            return out
        mb = int(max_bytes if max_bytes is not None
                 else self.cfg.warm_start_max_bytes)
        bs = float(budget_s if budget_s is not None
                   else self.cfg.warm_start_budget_s)
        loop = self._loop_thread
        if loop is None or not loop.is_alive():
            return dict(self._warm_start_now(mb, bs), supported=True)
        ev = threading.Event()
        box: list = []
        self._warm_req = (ev, box, mb, bs)
        self._wake.set()
        ev.wait(bs + 10.0)
        res = box[0] if box else {"pages": 0, "chains": 0,
                                  "wire_bytes": 0, "ms": 0.0}
        return dict(res, supported=True)

    def _warm_start_now(self, max_bytes: int, budget_s: float) -> dict:
        """Loop-thread warm-start worker. Plans from the CP index dump,
        restores chain by chain (each through its own ChainStream, chunk
        budgets and all), allocs pages, injects through the ONE fixed-
        shape donated-pool scatter and registers the digests at refcount
        zero (parked in the cached LRU: matchable, evictable, visible to
        prefix_summary). Page budget is capped by pool headroom (one
        request's worth of pages stays free) and the prefix-cache cap,
        so warming can neither starve the first admission nor trigger
        immediate evict-respill churn."""
        t0 = time.perf_counter()
        out = {"pages": 0, "chains": 0, "wire_bytes": 0, "ms": 0.0}
        deadline = t0 + max(0.1, budget_s)
        try:
            chains = self._kv_tier.restorable_chains(
                self.cfg.warm_start_max_chains)
        except Exception:  # noqa: BLE001 — warm start is best-effort
            logger.warning("warm start: chain enumeration failed",
                           exc_info=True)
            chains = []
        jnp = self._jnp
        mp = self.max_pages_per_seq
        for chain in chains:
            if time.perf_counter() >= deadline \
                    or out["wire_bytes"] >= max_bytes:
                break
            digs = [d for d in chain["digests"] if d]
            start = self.allocator.match_digest_chain(digs)
            if start >= len(digs):
                continue
            cs = self.allocator.cache_stats()
            budget_pages = self.allocator.available() - mp
            cap = self.cfg.prefix_cache_max_pages
            if cap > 0:
                budget_pages = min(budget_pages,
                                   cap - cs["evictable_pages"])
            n_take = min(len(digs) - start, budget_pages)
            if n_take <= 0:
                break
            stream = None
            try:
                stream = self._kv_tier.open_stream(
                    digs, start,
                    chunk_pages=self.cfg.kv_tier_chunk_pages,
                    window_bytes=self.cfg.kv_tier_stream_window_bytes,
                    timeout_s=self.cfg.kv_tier_chunk_timeout_s)
                c = start
                got = 0
                while got < n_take:
                    pairs, wire, _dec = stream.take(
                        max_pages=min(mp, n_take - got))
                    if not pairs:
                        if stream.exhausted \
                                or time.perf_counter() >= deadline:
                            break
                        time.sleep(0.002)
                        continue
                    pgs = self.allocator.alloc(len(pairs))
                    if pgs is None:
                        break
                    k_np = np.concatenate([k for k, _ in pairs], axis=2)
                    v_np = np.concatenate([v for _, v in pairs], axis=2)
                    t = len(pairs)
                    pad = np.zeros(k_np.shape[:2] + (mp - t,)
                                   + k_np.shape[3:], k_np.dtype)
                    with self._prof.compile_scope(
                            "kv_tier_inject", ("kv_tier_inject", mp),
                            mid_traffic=self.stats["requests"] > 0):
                        self.kv = self._tier_inject(
                            self.kv,
                            jnp.asarray(np.concatenate([k_np, pad],
                                                       axis=2)),
                            jnp.asarray(np.concatenate([v_np, pad],
                                                       axis=2)),
                            jnp.asarray(list(pgs) + [0] * (mp - t),
                                        jnp.int32))
                    self.allocator.insert_digest_chain(
                        digs[c:c + t], pgs, list(range(c, c + t)))
                    # decref to zero: registered pages park in the LRU,
                    # duplicate pages fall back to the free list
                    self.allocator.free(pgs)
                    c += t
                    got += t
                    out["pages"] += t
                    out["wire_bytes"] += wire
                if got:
                    out["chains"] += 1
            except Exception:  # noqa: BLE001 — degrade to a smaller set
                logger.warning("warm start: chain restore failed; "
                               "continuing", exc_info=True)
            finally:
                if stream is not None and not stream.exhausted:
                    stream.abort()
        out["ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        self.stats["warm_start_pages"] += out["pages"]
        self.stats["warm_start_ms"] = round(
            self.stats["warm_start_ms"] + out["ms"], 3)
        return out

    def _chain_digests(self, toks, limit: int,
                       ingress: Optional[list]) -> list[str]:
        """Hex chain digests for the first ``limit`` full pages, always
        recomputed over this engine's own tokens. Ingress digests are
        only cross-checked, never trusted: page-0 equality proves the
        proxy tokenizer agreed on the FIRST page, not on later ones — a
        version skew past page 0 would name different token content and
        restore KV that doesn't match the request. The chaining is
        blake2b over the token ids, microseconds against the cost of a
        wrong restore."""
        ps = self.cfg.page_size
        digest = b""
        digs = []
        for i in range(limit):
            digest = self._kvc._chain_digest(
                digest, toks[i * ps:(i + 1) * ps])
            digs.append(digest.hex())
        if ingress and digs and list(ingress[:limit]) != digs \
                and not self._ingress_skew_warned:
            self._ingress_skew_warned = True
            logger.warning(
                "ingress prefix digests disagree with local recompute "
                "(proxy/replica tokenizer skew?); affinity hints from "
                "this proxy will miss — using local digests")
        return digs

    def _kv_tier_begin_restore(self, req: _Request, m_loc: int) -> bool:
        """Open a pipelined restore stream for the tier-held chain pages
        past the local match. Returns False when there is nothing past
        the local match worth probing (or the stream could not open) —
        the caller then routes straight to prefill. True parks the
        request in _restoring; _restore_steps drives it from there."""
        try:
            ps = self.cfg.page_size
            toks = req.prompt_tokens
            limit = min((len(toks) - 1) // ps, len(req.pages))
            if limit <= m_loc:
                return False
            digs = self._chain_digests(toks, limit, req.ingress_digests)
            # floor the prefetch window at two raw chunks (raw bounds
            # the encoded wire bytes the window counts): a window
            # narrower than one chunk serializes the worker to sub-chunk
            # progress — it parks before every landing
            window = max(
                self.cfg.kv_tier_stream_window_bytes,
                2 * self.cfg.kv_tier_chunk_pages
                * self._kvc.page_raw_nbytes(self.model_cfg, ps))
            req.restore_stream = self._kv_tier.open_stream(
                digs, m_loc,
                chunk_pages=self.cfg.kv_tier_chunk_pages,
                window_bytes=window,
                timeout_s=self.cfg.kv_tier_chunk_timeout_s,
                on_ready=self._wake.set)
        except Exception:  # noqa: BLE001 - restore degrades to a miss
            logger.warning("kv-tier restore stream failed to open; cold "
                           "prefill instead", exc_info=True)
            req.restore_stream = None
            return False
        req.restore_started = time.perf_counter()
        req.restore_page0 = m_loc
        req.restore_pages = 0
        return True

    def _restore_steps(self) -> int:
        """Drive active restore streams (loop thread): take landed
        chunks, decode + scatter them into the request's pages, enforce
        the per-chunk budget, and finalize — full or PARTIAL — routing
        the request on to its suffix prefill. Decode+inject of landed
        chunks runs here while the streams' workers fetch ahead and the
        rest of this loop iteration prefills/decodes other requests:
        that concurrency is the restore latency the old fetch-then-
        inject path spent blocked."""
        with self._lock:
            active = list(self._restoring)
        if not active:
            return 0
        progressed = 0
        now_w = time.time()
        budget_s = max(self.cfg.kv_tier_chunk_timeout_s, 0.1)
        for req in active:
            stream = req.restore_stream
            if req.prefill_cancelled or (req.deadline is not None
                                         and now_w >= req.deadline):
                self._abort_prefilling(req)
                progressed += 1
                continue
            t0 = time.perf_counter()
            injected = 0
            try:
                pairs, wire, dec_ms = stream.take(
                    max_pages=self.max_pages_per_seq)
                if pairs:
                    injected = self._inject_pages(req, pairs)
                    req.restore_wire_bytes += wire
                    req.restore_decode_ms += dec_ms
            except Exception:  # noqa: BLE001 - degrade to partial/miss
                logger.warning("kv-tier chunk inject failed; keeping "
                               "landed pages, prefilling the rest",
                               exc_info=True)
                stream.abort()
            req.restore_blocked_ms += (time.perf_counter() - t0) * 1e3
            progressed += injected
            if stream.exhausted:
                self._finalize_restore(req)
                progressed += 1
            elif (time.monotonic() - stream.last_progress) > budget_s * 1.5:
                # per-chunk budget watchdog: the worker's own gets are
                # timeout-bounded, but a wedged load must not park the
                # request forever — cut the stream, keep what landed
                stream.abort()
        return progressed

    def _inject_pages(self, req: _Request, pairs: list) -> int:
        """Scatter decoded chain pages (in chain order, continuing at
        restore_page0 + restore_pages) into this request's pool pages —
        the same ONE fixed-shape donated-pool program as the old whole-
        chain restore, chunk-sized input zero-padded to it."""
        jnp = self._jnp
        ps = self.cfg.page_size
        mp = self.max_pages_per_seq
        pos0 = req.restore_page0 + req.restore_pages
        t = min(len(pairs), len(req.pages) - pos0)
        if t <= 0:
            return 0
        k_np = np.concatenate([k for k, _ in pairs[:t]], axis=2)
        v_np = np.concatenate([v for _, v in pairs[:t]], axis=2)
        shape = k_np.shape
        pad = np.zeros(shape[:2] + (mp - t,) + shape[3:], k_np.dtype)
        pages_vec = jnp.asarray(
            list(req.pages[pos0:pos0 + t]) + [0] * (mp - t), jnp.int32)
        with self._prof.compile_scope(
                "kv_tier_inject", ("kv_tier_inject", mp),
                mid_traffic=self.stats["requests"] > 0):
            self.kv = self._tier_inject(
                self.kv,
                jnp.asarray(np.concatenate([k_np, pad], axis=2)),
                jnp.asarray(np.concatenate([v_np, pad], axis=2)),
                pages_vec)
        req.restore_pages += t
        req.cached_tokens = (pos0 + t) * ps
        req.prefill_pos = req.cached_tokens
        req.restored_tokens += t * ps
        req.restore_bytes += int(k_np.nbytes) + int(v_np.nbytes)
        self.stats["restored_pages"] += t
        self.stats["tier_hit_tokens"] += t * ps
        return t

    def _finalize_restore(self, req: _Request) -> None:
        """Stream over (fully, partially, or not at all): stamp the
        attribution split, count a partial restore, and send the request
        to its suffix prefill — which starts exactly at the restored
        frontier, so a mid-chain fault costs recompute of the TAIL only,
        never of what already landed."""
        stream = req.restore_stream
        req.restore_stream = None
        req.restore_ms = (time.perf_counter()
                          - req.restore_started) * 1e3
        req.restore_overlap_ms = max(
            0.0, req.restore_ms - req.restore_blocked_ms)
        planned = stream.planned or 0
        if 0 < req.restore_pages < planned:
            self.stats["restore_partial"] += 1
            req.restore_partial = True
            _fr.emit("restore_partial", "WARNING",
                     request_id=req.request_id,
                     attrs={"restored_pages": int(req.restore_pages),
                            "planned_pages": int(planned)})
        if req.disagg:
            # fleet disagg (ISSUE 16): this restore carried a remote
            # prefill's KV — count the handoff and its wire/overlap
            # split regardless of whether the stream ran to plan (a
            # partial handoff still moved bytes and hid latency)
            self.stats["disagg_prefills"] += 1
            self.stats["handoff_bytes_wire"] += req.restore_wire_bytes
            self.stats["handoff_overlap_ms"] += req.restore_overlap_ms
        if req.resume_len:
            # the continuation's recovered-without-recompute accounting,
            # deferred from _admit until the restored frontier is final
            self.stats["failover_restored_tokens"] += req.cached_tokens
        with self._lock:
            if req in self._restoring:
                self._restoring.remove(req)
        self._route_admitted(req)

    def _prefill(self, req: _Request):
        """Dispatch prefill WITHOUT waiting for it: the sampled first token
        stays on device (fed to the next decode block as a scatter) and is
        recorded on the host by the harvest pipeline, in order, like any
        decode block's tokens."""
        jnp = self._jnp
        plen = len(req.prompt_tokens)
        bucket = self._bucket(plen)
        toks = np.full((1, bucket), 0, np.int32)
        toks[0, :plen] = req.prompt_tokens
        table = np.zeros((self.max_pages_per_seq,), np.int32)
        table[: len(req.pages)] = req.pages
        fn = self._prefill_fn(bucket)
        self._rng, sub = self._jax.random.split(self._rng)
        # a first-use prefill bucket compiles HERE, with a live request
        # waiting on it — warmup doesn't cover prompt buckets, so this is
        # always a mid-traffic compile when it fires
        with self._prof.phase("prefill"), self._prof.compile_scope(
                "prefill", ("prefill", bucket),
                mid_traffic=self.stats["requests"] > 0):
            tok_dev, self.kv = fn(
                self.params, self.kv, jnp.asarray(table), jnp.asarray(toks),
                jnp.int32(plen), sub,
                jnp.asarray([req.temperature], jnp.float32))
        self._arm_slot(req, table, tok_dev, plen)

    def _arm_slot(self, req: _Request, table, tok_dev, plen: int) -> None:
        """Publish a freshly prefilled slot to the decode loop: host/device
        state patch, first-token override (the on-device token carry knows
        nothing about fresh prefills), and a harvest entry for the sampled
        first token."""
        self._start_fetch(tok_dev)
        with self._lock:
            req.dispatched = 1
            self.page_tables[req.slot] = table
            self.seq_lens[req.slot] = plen
            self.slot_req[req.slot] = req
            self._dirty_slots[req.slot] = (plen, req.temperature)
            self._overrides[req.slot] = tok_dev
            self._pending.append((tok_dev, [(0, req.slot, req)], 1))
        if self._prefix_cache_on:
            # Index the prompt's FULL pages now (not at completion): the
            # writes are merely dispatched, but any matcher's reads are
            # dispatched later on the same ordered device stream, so a
            # concurrent same-prefix admission can already share. Partial
            # trailing pages are never indexed — and decode writes land at
            # positions >= plen, past every full prompt page — so a shared
            # page is never written after insertion (the would-be COW case
            # is excluded by construction; a FULL-prefix match instead
            # drops its last page and recomputes it into a private page,
            # copy-on-write by recompute).
            self.allocator.insert_prefix(
                req.prompt_tokens, req.pages, self.cfg.page_size)
        self.stats["prefills"] += 1

    def _prefill_chunks(self) -> int:
        """Dispatch ONE prefill chunk per in-progress chunked admission
        (loop thread). The final chunk's on-device sampled token arms the
        slot exactly like _prefill's; intermediate chunks only extend the
        cached KV. Chunks are dispatched async — the decode block that
        follows in this loop iteration queues behind them on the device
        stream, which is the interleaving."""
        jnp = self._jnp
        with self._lock:
            active = list(self._prefilling)
        now = time.time()
        for req in active:
            if req.prefill_cancelled or (req.deadline is not None
                                         and now >= req.deadline):
                self._abort_prefilling(req)
                continue
            plen = len(req.prompt_tokens)
            start = req.prefill_pos
            remaining = plen - start
            # prefill_chunk 0 disables chunking, but a cached-prefix
            # admission still rides this path (suffix-only prefill): the
            # whole suffix then goes as one chunk
            chunk = (self.cfg.prefill_chunk if self.cfg.prefill_chunk > 0
                     else remaining)
            final = remaining <= chunk
            clen = self._bucket(remaining) if final else chunk
            toks = np.zeros((1, clen), np.int32)
            seg = req.prompt_tokens[start: start + clen]
            toks[0, : len(seg)] = seg
            table = np.zeros((self.max_pages_per_seq,), np.int32)
            table[: len(req.pages)] = req.pages
            fn = self._chunk_fn(clen)
            self._rng, sub = self._jax.random.split(self._rng)
            with self._prof.phase("chunk_prefill"), self._prof.compile_scope(
                    "chunk", ("chunk", clen),
                    mid_traffic=self.stats["requests"] > 0):
                tok_dev, self.kv = fn(
                    self.params, self.kv, jnp.asarray(table),
                    jnp.asarray(toks), jnp.int32(start), jnp.int32(plen),
                    sub, jnp.asarray([req.temperature], jnp.float32))
            self.stats["attn_chunk_dispatches"] += 1
            req.prefill_pos = min(start + clen, plen)
            if req.prefill_pos >= plen:
                with self._lock:
                    self._prefilling.remove(req)
                self._arm_slot(req, table, tok_dev, plen)
        return len(active)

    def _abort_prefilling(self, req: _Request) -> None:
        """Release a mid-chunked-prefill request NOW (cancelled, or its
        deadline passed): slot, pages and tracking — not after the
        remaining chunks plus a decode step, which is how the _prefilling
        path used to leak pool capacity under cancel. Loop thread only:
        in-flight chunk dispatches may still write these pages, but the
        device stream is ordered, so any later prefill reusing them is
        dispatched — and therefore executes — after. The slot was never
        armed, so its device page-table row is still the zeros its
        previous occupant left."""
        expired = not getattr(req, "abandoned", False)
        if req.restore_stream is not None:
            # cut the stream first: its worker must stop landing chunks
            # for pages we are about to hand back to the pool
            req.restore_stream.abort()
            req.restore_stream = None
        with self._lock:
            if req in self._prefilling:
                self._prefilling.remove(req)
            if req in self._restoring:
                self._restoring.remove(req)
            if req.slot >= 0:
                self.free_slots.append(req.slot)
                req.slot = -1
            req.done = True
            req.finished_at = time.monotonic()
            if expired:
                req.error = "deadline exceeded"
                self.stats["shed_expired"] += 1
            else:
                self._requests.pop(req.request_id, None)
        self.allocator.free(req.pages)
        req.pages = []
        req.done_event.set()

    def _record_token(self, req: _Request, tok: int) -> None:
        """Append a sampled token; mark done on stop/max. Lock held."""
        if req.done:
            return
        now = time.monotonic()
        if req.first_token_at is None:
            req.first_token_at = now
        elif req.last_token_at is not None:
            gap = now - req.last_token_at
            req.itl_gaps.append(gap)
            self._prof.record_itl(gap)
        req.last_token_at = now
        req.generated.append(tok)
        self.stats["tokens_out"] += 1
        hit_stop = (req.stop_token is not None and tok == req.stop_token)
        if hit_stop or len(req.generated) >= req.max_tokens:
            if hit_stop:
                req.generated.pop()  # don't emit the stop token
            req.done = True
            req.finished_at = time.monotonic()

    def _select_block(self) -> int:
        """Decode-block tier for the next dispatch (lock held). k is
        STATIC to the jitted program: only three values ever occur (1
        while admissions wait, pressure_decode_block while requests queue
        for slots, decode_block otherwise), so at most three programs
        compile per width. The slot-starved middle tier trades dispatch
        amortization for TTFT: a finishing request's stop token is
        detected (and its slot freed for the queue) within
        ~pipeline_depth*k steps, so big blocks at saturation hold slots
        long past completion.

        With speculative decoding on, the idle tier is additionally capped
        at spec_draft_len: a draft can only continue the CURRENT head
        token, and the engine probes for drafts once per loop iteration,
        so long decode blocks would skip almost every draft opportunity
        (the head lands mid-block). Verify rounds are themselves k+1 fused
        steps, so speculation recovers the dispatch amortization the
        shorter blocks give up — and on non-repetitive traffic the cap is
        the documented cost of leaving the flag on."""
        if self._admissions_blocked():
            return 1
        if self._waiting:
            return max(1, min(self.cfg.pressure_decode_block,
                              self.cfg.decode_block))
        k = self.cfg.decode_block
        if self._spec_on:
            k = min(k, max(1, self.cfg.spec_draft_len))
        return k

    def _flush_slot_patches(self, dirty: dict, overrides: dict):
        """Apply queued slot-state patches at the fixed B+1 shape (trash-
        row padded — see the compile-stall note on _patch_state) and
        return the patched device token vector. Shared by the decode and
        verify-k dispatch paths; loop thread only."""
        jnp = self._jnp
        trash_row = self.cfg.max_batch_size
        if dirty:
            # fixed-shape patch: pad to B+1 rows onto the trash row (whose
            # state is all-zeros by invariant), so ONE compiled scatter
            # covers every dirty-count
            order = sorted(dirty)
            pad = (trash_row + 1) - len(order)
            didx = jnp.asarray(order + [trash_row] * pad, jnp.int32)
            ptv = np.zeros((trash_row + 1, self.max_pages_per_seq), np.int32)
            ptv[: len(order)] = self.page_tables[order]
            slv = np.zeros((trash_row + 1,), np.int32)
            slv[: len(order)] = [dirty[i][0] for i in order]
            tv = np.zeros((trash_row + 1,), np.float32)
            tv[: len(order)] = [dirty[i][1] for i in order]
            self._pt_dev, self._sl_dev, self._temps_dev = self._patch_state(
                self._pt_dev, self._sl_dev, self._temps_dev, didx,
                jnp.asarray(ptv), jnp.asarray(slv), jnp.asarray(tv))
        toks = self._dev_tokens
        if toks is None:
            toks = jnp.zeros((self.cfg.max_batch_size + 1,), jnp.int32)
        if overrides:
            # values are device scalars from async prefills (or host ints
            # from verify-round acceptance): stacking and scattering stays
            # on device — no host sync. Same fixed-shape padding (trash-row
            # writes of 0) as the state patch.
            if self._zero_tok is None:
                self._zero_tok = jnp.int32(0)
            pad = (trash_row + 1) - len(overrides)
            oidx = jnp.asarray(
                list(overrides.keys()) + [trash_row] * pad, jnp.int32)
            ovals = jnp.stack(
                [jnp.asarray(v, jnp.int32) for v in overrides.values()]
                + [self._zero_tok] * pad)
            toks = self._patch_toks(toks, oidx, ovals)
        return toks

    def _step(self) -> bool:
        """Dispatch the iteration's device work: a speculative verify-k
        round for slots with drafts (spec_decode_enabled), then one fused
        decode block for the rest."""
        did_spec = self._spec_on and self._spec_step()
        return self._decode_step() or did_spec

    def _decode_step(self) -> bool:
        """Dispatch one fused decode block (1..decode_block steps) without
        waiting for its result; harvest PIPELINE_DEPTH blocks behind.
        Device execution is a single ordered stream, so an in-flight block
        that still references a freed slot's pages runs BEFORE any later
        prefill that reuses them.

        Steady-state decode is ONE jitted call with all-device arguments
        (page tables, seq lens, temps, last tokens, rng all live on device;
        slot admissions patch them with small eager updates). On a tunneled
        chip every dispatch costs a round trip, so the block fusion brings
        per-token cost to ~RTT/decode_block; block size drops to 1 while
        admissions are pending so new requests don't wait a whole block."""
        jnp = self._jnp
        with self._lock:
            snapshot = [(i, i, req) for i, req in enumerate(self.slot_req)
                        if req is not None
                        and req.dispatched < req.max_tokens
                        and not req.spec_inflight]
            if not snapshot:
                return False
            # Overshoot past a request's max_tokens is by-design safe:
            # extra writes land in the slot's own tail pages or the trash
            # page, and harvest discards them.
            k = self._select_block()
            self._last_block = k
            dirty, self._dirty_slots = self._dirty_slots, {}
            overrides, self._overrides = self._overrides, {}
            for _col, _slot, req in snapshot:
                req.dispatched += k
        # decode_dispatch times the HOST cost of getting the block onto
        # the device stream (patch flush + jit dispatch); the result sync
        # is the harvest phase. The pipeline-trim harvest below is
        # excluded — it's already sampled inside _harvest_one.
        t0 = time.perf_counter() if self._prof.enabled else 0.0
        toks = self._flush_slot_patches(dirty, overrides)
        # bucketed width: pack the active slots, pad with the trash row —
        # a lightly loaded engine runs a narrow program
        active_slots = [slot for _c, slot, _r in snapshot]
        w = self._bucket_width(len(active_slots))
        trash = self.cfg.max_batch_size
        idx = jnp.asarray(
            active_slots + [trash] * (w - len(active_slots)), jnp.int32)
        snapshot = [(col, slot, req)
                    for col, (_c, slot, req) in enumerate(snapshot)]
        with self._prof.compile_scope(
                "decode", ("decode", w, k),
                mid_traffic=self.stats["requests"] > 0):
            all_toks, self._dev_tokens, self.kv, self._sl_dev, self._rng = \
                self._decode(self.params, self.kv, self._pt_dev,
                             self._sl_dev, toks, self._rng,
                             self._temps_dev, idx, k)
        self._start_fetch(all_toks)
        self._pending.append((all_toks, snapshot, k))
        self.stats["steps"] += k
        self.stats["attn_decode_dispatches"] += 1
        if self._prof.enabled:
            self._prof.record("decode_dispatch", time.perf_counter() - t0)
        if len(self._pending) > self.PIPELINE_DEPTH:
            self._harvest_one()
        return True

    # ---- speculative decoding ------------------------------------------
    def _propose_locked(self, req: _Request) -> list[int]:
        """Draft tokens for one slot (lock held). Greedy slots only — the
        bit-identity guarantee is a greedy property; non-greedy slots ride
        the normal decode path untouched. The draft is capped so a fully
        accepted round cannot emit past max_tokens."""
        if req.temperature != 0.0:
            return []
        remaining = req.max_tokens - len(req.generated)
        if remaining <= 1:
            return []
        if req.spec is None:
            from ray_tpu.serve.llm import spec_decode
            req.spec = spec_decode.NGramProposer(
                self.cfg.spec_ngram_max, self.cfg.spec_draft_len)
        draft = req.spec.propose(req.prompt_tokens + req.generated)
        return draft[: remaining - 1]

    def _dispatch_verify(self, rows) -> None:
        """Dispatch ONE verify-k round for ``rows`` of (slot, req, draft,
        base_len) whose host state is exact (just drained or just
        harvested). Loop thread only; lock NOT held."""
        jnp = self._jnp
        k = self.cfg.spec_draft_len
        with self._lock:
            for _slot, req, _draft, _base in rows:
                req.spec_inflight = True
                req.dispatched += k + 1
            dirty, self._dirty_slots = self._dirty_slots, {}
            overrides, self._overrides = self._overrides, {}
        t0 = time.perf_counter() if self._prof.enabled else 0.0
        toks = self._flush_slot_patches(dirty, overrides)
        spec_slots = [slot for slot, _r, _d, _b in rows]
        w = self._bucket_width(len(spec_slots))
        trash = self.cfg.max_batch_size
        idx = jnp.asarray(
            spec_slots + [trash] * (w - len(spec_slots)), jnp.int32)
        draft_mat = np.full((w, k), -1, np.int32)
        entry = []  # (col, slot, req, draft, base_len)
        for col, (slot, req, draft, base_len) in enumerate(rows):
            draft_mat[col, : len(draft)] = draft
            entry.append((col, slot, req, draft, base_len))
        with self._prof.compile_scope(
                "verify", ("verify", w, k),
                mid_traffic=self.stats["requests"] > 0):
            all_toks, self._dev_tokens, self.kv, self._sl_dev, self._rng = \
                self._verify(self.params, self.kv, self._pt_dev,
                             self._sl_dev, toks, self._rng,
                             self._temps_dev, idx, jnp.asarray(draft_mat))
        self._start_fetch(all_toks)
        self._pending.append((all_toks, entry, ("spec", k)))
        self.stats["steps"] += k + 1
        self.stats["attn_verify_dispatches"] += 1
        if self._prof.enabled:
            self._prof.record("verify_dispatch", time.perf_counter() - t0)

    def _spec_step(self) -> bool:
        """TRANSITION decode-mode slots with drafts into verify rounds.

        Speculation needs the host's view of a slot to be authoritative
        (drafts continue the slot's true token sequence, and rollback
        needs its true cache length), so entering spec mode drains the
        in-flight pipeline once — every entry's successors are already
        dispatched on the ordered device stream, so those harvests are
        bounded by work the device is retiring anyway. After that the slot
        CHAINS drain-free: each verify harvest leaves its host state
        exact, so _apply_verify re-proposes and dispatches the next round
        directly, and the slot only falls back into decode blocks when a
        draft misses. Slots without a draft are left to _decode_step in
        the same iteration (their blocks never touch a chained slot:
        spec_inflight excludes it from decode snapshots). A cheap
        pre-check on the (possibly pipeline-stale) host context avoids
        paying the drain when nothing would draft."""
        with self._lock:
            # gate on generated (host truth lower bound), NOT dispatched:
            # pipelined decode runs dispatched ahead to max_tokens within a
            # few blocks, which would silence speculation for the rest of
            # the generation. A stale-context false positive just costs the
            # drain (the post-drain re-propose is authoritative).
            if not any(req is not None and not req.done
                       and len(req.generated) < req.max_tokens
                       and not req.spec_inflight
                       and self._propose_locked(req)
                       for req in self.slot_req):
                return False
            n = len(self._pending)
        # drain the entries present NOW: chained verify rounds appended by
        # these harvests belong to already-speculating slots and never
        # reference the transitioning ones
        for _ in range(n):
            self._harvest_one()
        with self._lock:
            rows = []  # (slot, req, draft, base_len)
            for slot, req in enumerate(self.slot_req):
                if req is None or req.spec_inflight \
                        or req.dispatched >= req.max_tokens:
                    continue
                draft = self._propose_locked(req)
                if not draft:
                    continue
                # device cache length for this slot: prompt + every
                # recorded token except the current one (which is the
                # verify round's position-0 input). Exact because the
                # pipeline was just drained.
                base_len = len(req.prompt_tokens) + len(req.generated) - 1
                rows.append((slot, req, draft, base_len))
        if not rows:
            return False
        self._dispatch_verify(rows)
        return True

    def _apply_verify(self, dev_toks, rows, k: int) -> None:
        """Record a verify round: per slot, accept the longest draft
        prefix matching the per-position outputs, emit accepted+1 tokens
        through _record_token (stream ordering unchanged), and roll the
        slot's seq_len back past the rejected tail via the dirty-slot
        patch. Rollback is pure length accounting — no allocator calls, so
        shared prefix-cache pages are never decreffed or evicted by a
        rejection; the junk KV past the new length sits in the slot's own
        suffix pages and is overwritten before it can be attended.

        Slots whose fresh context drafts again chain straight into the
        next verify round (their just-harvested host state is exact — no
        pipeline drain needed); the rest drop back to decode blocks."""
        from ray_tpu.serve.llm import spec_decode
        if self._prof.enabled:
            t0 = time.perf_counter()
            host = np.asarray(dev_toks)  # device sync (oldest round)
            self._prof.record("harvest", time.perf_counter() - t0)
            host = host.reshape(k + 1, -1)
        else:
            host = np.asarray(dev_toks).reshape(k + 1, -1)
        finished: list[_Request] = []
        chain = []  # (slot, req, draft, base_len)
        with self._lock:
            self.stats["spec_rounds"] += 1
            for col, slot, req, draft, base_len in rows:
                req.spec_inflight = False
                outs = [int(host[s, col]) for s in range(k + 1)]
                a = spec_decode.accept_length(draft, outs)
                self.stats["spec_drafted_tokens"] += len(draft)
                self.stats["spec_accepted_tokens"] += a
                emitted = 0
                for tok in outs[: a + 1]:
                    if req.done:
                        break  # stop token inside the accepted run
                    self._record_token(req, tok)
                    emitted += 1
                if req.done:
                    finished.append(req)
                    if self.slot_req[slot] is req:
                        self.slot_req[slot] = None
                        self.free_slots.append(slot)
                        self.page_tables[slot] = 0
                        self.seq_lens[slot] = 0
                        self._dirty_slots[slot] = (0, 0.0)
                    continue
                # roll back: device seq_len advanced k+1 during the round;
                # the truth is base_len + emitted (the accepted tokens are
                # in cache, the last emitted token is the new current one)
                new_len = base_len + emitted
                self.seq_lens[slot] = new_len
                self._dirty_slots[slot] = (new_len, req.temperature)
                self._overrides[slot] = outs[emitted - 1]
                req.dispatched = len(req.generated)
                nxt = self._propose_locked(req)
                if nxt:
                    chain.append((slot, req, nxt, new_len))
        if chain:
            self._dispatch_verify(chain)
        self._finish_requests(finished)

    def _harvest_one(self) -> None:
        """Block on the OLDEST in-flight block's tokens and record them.

        Entries are decode blocks (tokens [k, W] at the PACKED bucket
        width — the column is the request's position in that block's
        packed index vector, NOT its slot id), prefill first-tokens
        (scalar, column 0) with snapshot rows (token_column, slot,
        request), or verify-k rounds (meta ("spec", k), handled by
        _apply_verify)."""
        with self._lock:
            if not self._pending:
                return
            dev_toks, snapshot, k = self._pending.pop(0)
        if isinstance(k, tuple):  # ("spec", draft_len) verify round
            self._apply_verify(dev_toks, snapshot, k[1])
            return
        if self._prof.enabled:
            # THE device sync: all device slowness (or a fetch that wasn't
            # prefetched) surfaces here, attributed as "harvest" instead
            # of smeared across the loop
            t0 = time.perf_counter()
            host_toks = np.asarray(dev_toks)  # sync point: oldest block only
            self._prof.record("harvest", time.perf_counter() - t0)
        else:
            host_toks = np.asarray(dev_toks)
        host_toks = host_toks.reshape(k, -1)
        finished: list[_Request] = []
        with self._lock:
            for step in range(k):
                for col, slot, req in snapshot:
                    if req.done:
                        continue  # stop/max lag: discard overshoot tokens
                    self._record_token(req, int(host_toks[step, col]))
                    if req.done:
                        finished.append(req)
                        if self.slot_req[slot] is req:
                            self.slot_req[slot] = None
                            self.free_slots.append(slot)
                            self.page_tables[slot] = 0
                            self.seq_lens[slot] = 0
                            # invalidate the DEVICE row too: a stale device
                            # page table keeps scattering this slot's junk
                            # KV into pages after they're reallocated
                            self._dirty_slots[slot] = (0, 0.0)
        self._finish_requests(finished)

    def _finish_requests(self, finished: list[_Request]) -> None:
        """Completion tail shared by decode and verify harvests: free
        pages, release waiters, emit trace spans, reap abandoned."""
        for req in finished:
            self.allocator.free(req.pages)
            req.pages = []
        for req in finished:
            req.done_event.set()
            if req.trace_ctx:
                from ray_tpu.observability import tracing
                tracing.record_span(
                    "llm.generate", req.submitted_wall, time.time(),
                    parent=req.trace_ctx, kind="llm",
                    attrs={"request_id": req.request_id,
                           "prompt_tokens": len(req.prompt_tokens),
                           "generated_tokens": len(req.generated)})
            if getattr(req, "abandoned", False):
                with self._lock:
                    self._requests.pop(req.request_id, None)
