"""Replay buffer (ref: rllib/utils/replay_buffers/replay_buffer.py).

Numpy ring storage on the host — replay is random-access and mutation-heavy,
the wrong shape for device memory; sampled minibatches move to the device as
one contiguous batch per train step.
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, observation_dim: int, seed: int = 0):
        self._cap = capacity
        self._obs = np.zeros((capacity, observation_dim), np.float32)
        self._next_obs = np.zeros((capacity, observation_dim), np.float32)
        self._actions = np.zeros((capacity,), np.int32)
        self._rewards = np.zeros((capacity,), np.float32)
        self._dones = np.zeros((capacity,), np.float32)
        self._size = 0
        self._head = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: dict) -> None:
        n = len(batch["actions"])
        idx = (self._head + np.arange(n)) % self._cap
        self._obs[idx] = batch["obs"]
        self._next_obs[idx] = batch["next_obs"]
        self._actions[idx] = batch["actions"]
        self._rewards[idx] = batch["rewards"]
        self._dones[idx] = batch["dones"]
        self._head = (self._head + n) % self._cap
        self._size = min(self._size + n, self._cap)

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, batch_size)
        return {"obs": self._obs[idx], "next_obs": self._next_obs[idx],
                "actions": self._actions[idx], "rewards": self._rewards[idx],
                "dones": self._dones[idx]}


class _SumTree:
    """Binary sum-tree over leaf priorities: O(log n) update and
    prefix-sum sampling (ref: rllib/utils/replay_buffers/segment_tree)."""

    def __init__(self, capacity: int):
        self._cap = 1
        while self._cap < capacity:
            self._cap *= 2
        self._tree = np.zeros(2 * self._cap, np.float64)

    def set(self, idx: np.ndarray, value: np.ndarray) -> None:
        i = np.asarray(idx) + self._cap
        self._tree[i] = value
        # all leaves share one depth, so every index walks to the root in
        # lockstep; one vectorized parent recompute per level
        i //= 2
        while i[0] >= 1 if np.ndim(i) else i >= 1:
            uj = np.unique(i)
            uj = uj[uj >= 1]
            if not len(uj):
                break
            self._tree[uj] = self._tree[2 * uj] + self._tree[2 * uj + 1]
            i = uj // 2

    def total(self) -> float:
        return float(self._tree[1])

    def prefix_index(self, mass: np.ndarray) -> np.ndarray:
        """Leaf index whose cumulative-priority interval contains mass."""
        mass = np.asarray(mass, np.float64).copy()
        idx = np.ones(len(mass), np.int64)
        while idx[0] < self._cap:
            left = 2 * idx
            left_sum = self._tree[left]
            go_right = mass > left_sum
            mass = np.where(go_right, mass - left_sum, mass)
            idx = np.where(go_right, left + 1, left)
        return idx - self._cap


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (ref:
    rllib/utils/replay_buffers/prioritized_replay_buffer.py — the PER
    scheme): sample probability ~ priority^alpha via a sum-tree,
    importance-sampling weights (1/(N*P))^beta returned per sample, and
    update_priorities(idx, td_error) after each learner step."""

    def __init__(self, capacity: int, observation_dim: int, seed: int = 0,
                 alpha: float = 0.6, beta: float = 0.4):
        super().__init__(capacity, observation_dim, seed=seed)
        self._alpha = alpha
        self.beta = beta
        self._tree = _SumTree(capacity)
        self._max_prio = 1.0

    def add_batch(self, batch: dict) -> None:
        n = len(batch["actions"])
        idx = (self._head + np.arange(n)) % self._cap
        super().add_batch(batch)
        # new experience enters at max priority so it is seen at least once
        self._tree.set(idx, np.full(n, self._max_prio ** self._alpha))

    def sample(self, batch_size: int) -> dict:
        total = self._tree.total()
        mass = self._rng.uniform(0.0, total, batch_size)
        idx = self._tree.prefix_index(mass)
        idx = np.minimum(idx, self._size - 1)
        prios = self._tree._tree[idx + self._tree._cap]
        probs = np.maximum(prios, 1e-12) / max(total, 1e-12)
        weights = (self._size * probs) ** (-self.beta)
        weights = weights / weights.max()
        return {"obs": self._obs[idx], "next_obs": self._next_obs[idx],
                "actions": self._actions[idx], "rewards": self._rewards[idx],
                "dones": self._dones[idx],
                "weights": weights.astype(np.float32),
                "idx": idx.astype(np.int64)}

    def update_priorities(self, idx: np.ndarray, td_errors: np.ndarray) -> None:
        prio = np.abs(np.asarray(td_errors, np.float64)) + 1e-6
        self._max_prio = max(self._max_prio, float(prio.max()))
        self._tree.set(np.asarray(idx), prio ** self._alpha)
