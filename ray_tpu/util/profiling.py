"""Profiling helpers: XLA/XPlane traces + task timelines.

TPU-native analog of the reference's profiling surface (SURVEY.md §5.1:
chrome-trace timeline in _private/state.py:438, py-spy/torch-profiler hooks).
On TPU the profiler of record is the XLA/XPlane one — `jax.profiler` —
whose dumps open in TensorBoard/XProf and show MXU utilization, HBM traffic
and ICI collectives per op. The task-level chrome trace lives in
ray_tpu.util.state.timeline().
"""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def profile_trace(logdir: str, *, host_tracer_level: int = 2):
    """Capture an XPlane trace of everything inside the block.

    Usage (inside a train fn)::

        with profile_trace("/tmp/prof"):
            for _ in range(10):
                state, metrics = step(state, batch)
        # then: tensorboard --logdir /tmp/prof  (Profile tab)
    """
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir, create_perfetto_link=False)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a profile_trace (shows as a span in XProf).
    Usage: `with annotate("data-load"): ...`"""
    import jax

    return jax.profiler.TraceAnnotation(name)


def save_device_memory_profile(path: str) -> str:
    """Dump the current device (HBM) memory profile in pprof format —
    the 'why is my model OOMing' tool."""
    import jax

    jax.profiler.save_device_memory_profile(path)
    return path


def profile_step(fn, *args, logdir: str = "/tmp/ray_tpu_prof", **kwargs):
    """One-shot: trace a single call of `fn` and return its result."""
    with profile_trace(logdir):
        out = fn(*args, **kwargs)
        import jax

        jax.block_until_ready(out)
    return out


def dump_thread_stacks() -> str:
    """Every thread's Python stack as text (named), for on-demand hang
    diagnosis (ref: dashboard/modules/reporter/profile_manager.py:191 —
    the reference shells out to py-spy; a pure-Python snapshot needs no
    debugger attach and works from an RPC handler)."""
    import sys
    import threading
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid})\n"
                   + "".join(traceback.format_stack(frame)))
    return "\n".join(out)
