"""Public trainers: DataParallelTrainer + JaxTrainer.

TPU-native analog of the reference's trainer surface
(/root/reference/python/ray/train/v2/api/data_parallel_trainer.py —
DataParallelTrainer.fit:118; train/v2/jax/jax_trainer.py:19 JaxTrainer). In
this framework the JaxTrainer is the PRIMARY trainer (SURVEY.md §7 step 6) —
SPMD over an ICI×DCN mesh with `jax.distributed` bootstrap — rather than a
backend bolted onto torch.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on a gang of rank actors."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[dict] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 backend_fn: Optional[Callable] = None,
                 scaling_policy=None):
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self._scaling_config = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets
        self._resume_from_checkpoint = resume_from_checkpoint
        self._backend_fn = backend_fn
        self._scaling_policy = scaling_policy

    def fit(self) -> Result:
        controller = TrainController(
            self._train_loop,
            train_fn_config=self._train_loop_config,
            scaling_config=self._scaling_config,
            run_config=self._run_config,
            datasets=self._datasets,
            backend_fn=self._backend_fn,
            resume_from_checkpoint=self._resume_from_checkpoint,
            scaling_policy=self._scaling_policy)
        return controller.run()


def _jax_backend(ctx) -> None:
    """Per-worker JAX bootstrap, run in the worker actor before the train fn.

    Reference: _JaxBackend / _setup_jax_tpu_environment
    (train/v2/jax/config.py) — rank 0 publishes a coordinator address; every
    worker calls jax.distributed.initialize(addr, n, rank). Single-worker
    groups skip distributed init (single-host SPMD needs none).
    """
    world = ctx.get_world_size()
    rank = ctx.get_world_rank()
    if world <= 1:
        return
    import os
    import socket

    import ray_tpu
    from ray_tpu.train.sync import SynchronizationActor

    name = f"_jax_coord_{ctx.get_experiment_name()}"
    if rank == 0:
        try:
            sync = ray_tpu.get_actor(name, timeout=0.5)
        except Exception:  # noqa: BLE001 - first creation
            sync = SynchronizationActor.options(name=name).remote(world)
    else:
        sync = ray_tpu.get_actor(name, timeout=30.0)

    port = int(os.environ.get("RAY_TPU_JAX_COORD_PORT", "0")) or \
        _free_port()
    addr = f"{socket.gethostbyname(socket.gethostname())}:{port}"
    coord = ray_tpu.get(sync.broadcast_from_rank_zero.remote(rank, addr),
                        timeout=120.0)

    import jax
    try:
        # Bounded: the free-port choice is racy (another process can grab
        # it between probe and bind) and a worker connecting to a hijacked
        # port wedges INSIDE the C++ coordination client where no Python
        # watchdog can see it. A timeout converts the wedge into a worker
        # failure the trainer's FailurePolicy retries with a fresh port.
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=world, process_id=rank,
            initialization_timeout=120)
    except RuntimeError as e:
        # Already initialized (worker restart reusing the process) is fine.
        if "already" not in str(e).lower():
            raise


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class JaxTrainer(DataParallelTrainer):
    """SPMD JAX training over a TPU slice — the flagship trainer.

    Each worker is one JAX process on one TPU host; inside the train fn user
    code builds a mesh (ray_tpu.parallel.mesh) spanning the slice and runs a
    pjit train step (ray_tpu.train.spmd). Multi-host wiring
    (jax.distributed.initialize) is handled by the backend hook.
    """

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[dict] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 use_distributed: Optional[bool] = None):
        scaling = scaling_config or ScalingConfig()
        if use_distributed is None:
            use_distributed = scaling.num_workers > 1 and scaling.use_tpu
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            scaling_config=scaling,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
            backend_fn=_jax_backend if use_distributed else None)
