"""Per-process worker runtime — linked into every driver and worker.

TPU-native analog of the reference's CoreWorker
(/root/reference/src/ray/core_worker/core_worker.h:165): owns task submission
(SubmitTask :852, SubmitActorTask :934), task execution (ExecuteTask :1481,
HandlePushTask :1151), Put/Get/Wait (:479,:655,:695), the in-process memory
store, the shared-memory store client, ownership-based reference counting, and
actor execution queues (task_execution/actor_scheduling_queue.cc ordering,
concurrency groups, async actors on an event loop).

Results follow the reference's split: small values ride the push reply into the
owner's memory store; large values are sealed into the node's shared-memory
store and fetched on demand (core_worker.cc return-path semantics).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

from ray_tpu.core.config import get_config
from ray_tpu.core.function_manager import FunctionManager
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.memory_store import MemoryStore
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import ShmClient
from ray_tpu.core.rpc import ClientPool, DeferredReply, RpcClient, RpcServer
from ray_tpu.core.serialization import SerializationContext, SerializedObject
from ray_tpu.core.submitter import ActorTaskSubmitter, NormalTaskSubmitter
from ray_tpu.core.task_manager import TaskManager
from ray_tpu.core.task_spec import (
    DefaultStrategy,
    SchedulingStrategy,
    TaskArg,
    TaskSpec,
    TaskType,
)
from ray_tpu.core.refcount import ReferenceCounter
from ray_tpu.core import deadline as request_deadline
from ray_tpu.observability import tracing
from ray_tpu.exceptions import (
    DeadlineExceededError,
    GetTimeoutError,
    ObjectLostError,
    ObjectStoreFullError,
    TaskCancelledError,
    TaskError,
)

logger = logging.getLogger(__name__)

# A push_task_batch frame older than this ships completed sub-replies to the
# owner eagerly instead of waiting for the frame's aggregate reply — a fast
# concurrent call must not be held hostage by a slow batch-mate. Bursts of
# quick tasks finish under the threshold and pay one aggregate frame.
_EARLY_REPLY_S = 0.01


class _BatchFrame:
    """Aggregates the sub-replies of one push_task_batch frame (see
    WorkerRuntime._h_push_task_batch). The frame janitor calls flush_early on
    frames that outlive _EARLY_REPLY_S, shipping completed sub-replies to the
    owner ahead of the aggregate; the owner deduplicates (the aggregate's
    copy finds the task no longer pending)."""

    __slots__ = ("rt", "specs", "agg", "t0", "_lock", "_slots",
                 "_early_sent", "_remaining", "complete")

    def __init__(self, rt, specs):
        self.rt = rt
        self.specs = specs
        self.agg = DeferredReply()
        self.t0 = time.monotonic()
        self._lock = threading.Lock()
        self._slots: list = [None] * len(specs)
        self._early_sent = [False] * len(specs)
        self._remaining = len(specs)
        self.complete = False

    def finisher(self, i: int):
        return lambda ok, res: self.done(i, ok, res)

    def done(self, i: int, ok: bool, res):
        if not ok:
            res = {"results": [],
                   "error": f"executor error: {res!r}",
                   "attempt": self.specs[i].attempt_number}
        with self._lock:
            self._slots[i] = res
            self._remaining -= 1
            last = self._remaining == 0
            if last:
                self.complete = True
        if last:
            self.agg.send({"replies": self._slots})
        # Completed-but-unsent sub-replies of an overdue frame are shipped by
        # the janitor (≤ one _EARLY_REPLY_S period away) — NOT inline here:
        # done() runs on the task-execution thread, and a blocking notify to
        # a dead owner (connect retries up to rpc_connect_timeout_s) would
        # freeze task execution for every other owner's tasks on this worker.

    def flush_early(self):
        to_send = []
        with self._lock:
            if self.complete:
                return
            for i, res in enumerate(self._slots):
                if res is not None and not self._early_sent[i] \
                        and self.specs[i].owner_addr is not None:
                    self._early_sent[i] = True
                    to_send.append((self.specs[i], res))
        for spec, res in to_send:
            addr = tuple(spec.owner_addr)
            # Runs on the shared janitor thread: a dead owner must not
            # stall other frames' early replies, so connects are bounded
            # and failing owners are skipped for a while (the aggregate
            # reply still carries every result).
            if self.rt._early_send_suspended(addr):
                continue
            try:
                # latency hint only — the aggregate reply below still
                # carries every result if this never lands
                # graftlint: fire-and-forget
                self.rt.peer_pool.get(addr).notify(
                    "task_reply_early",
                    {"task_id": spec.task_id, "reply": res},
                    connect_timeout=0.5)
            except Exception:  # noqa: BLE001 — the aggregate still carries it
                self.rt._suspend_early_sends(addr)


class _NormalTaskQueue:
    """Sequential normal-task execution with blocked-task yield.

    One runner thread drains the queue (the reference's
    NormalSchedulingQueue); when the running task blocks in get()/wait()
    (signalled via on_blocked), a new runner starts for the next queued
    task — mirroring the raylet's release-CPU-while-blocked oversubscribe
    (node_manager blocked-worker handling) at worker scope. Pipelined
    pushes from the submitter therefore can't deadlock tasks that
    rendezvous with each other."""

    # An idle runner lingers before exiting: thread churn is pure overhead
    # on the task hot path, and rapid create/destroy of executor threads is
    # exactly the profile that tickled arrow-mimalloc's thread-local-heap
    # fault (see ray_tpu/__init__.py ARROW_DEFAULT_MEMORY_POOL note).
    IDLE_LINGER_S = 5.0

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._active = 0  # runners currently NOT blocked
        self._tl = threading.local()

    def submit(self, run) -> None:
        with self._lock:
            self._queue.append(run)
            start = self._active == 0
            if start:
                self._active += 1
            else:
                self._cv.notify()
        if start:
            threading.Thread(target=self._loop, name="task-exec",
                             daemon=True).start()

    def _loop(self):
        self._tl.runner = True
        self._tl.block_depth = 0
        while True:
            with self._lock:
                if not self._queue:
                    self._cv.wait(timeout=self.IDLE_LINGER_S)
                    if not self._queue:
                        self._active -= 1
                        return
                run = self._queue.popleft()
            run()

    def is_runner(self) -> bool:
        return bool(getattr(self._tl, "runner", False))

    def on_blocked(self):
        """Current runner is about to block; let the next queued task run."""
        if not getattr(self._tl, "runner", False):
            return
        self._tl.block_depth = getattr(self._tl, "block_depth", 0) + 1
        if self._tl.block_depth != 1:
            return
        start = False
        with self._lock:
            self._active -= 1
            if self._queue and self._active == 0:
                self._active += 1
                start = True
        if start:
            threading.Thread(target=self._loop, name="task-exec",
                             daemon=True).start()

    def on_unblocked(self):
        if not getattr(self._tl, "runner", False):
            return
        self._tl.block_depth -= 1
        if self._tl.block_depth == 0:
            with self._lock:
                self._active += 1


class _TaskContext:
    """Per-execution task context. Backed by contextvars (not
    threading.local) so concurrent coroutines of an async actor — which
    interleave on ONE event-loop thread — each see their own task_id and
    put counter (asyncio Tasks copy the context at creation)."""

    def __init__(self):
        import contextvars
        self._task_id = contextvars.ContextVar("rtpu_task_id", default=None)
        self._put = contextvars.ContextVar("rtpu_put_counter", default=0)
        self._child = contextvars.ContextVar("rtpu_child_counter", default=0)

    @property
    def task_id(self) -> TaskID | None:
        return self._task_id.get()

    @task_id.setter
    def task_id(self, v) -> None:
        self._task_id.set(v)

    @property
    def put_counter(self) -> int:
        return self._put.get()

    @put_counter.setter
    def put_counter(self, v: int) -> None:
        self._put.set(v)

    @property
    def child_counter(self) -> int:
        return self._child.get()

    @child_counter.setter
    def child_counter(self, v: int) -> None:
        self._child.set(v)


@dataclass
class _ActorExecState:
    instance: Any = None
    actor_id: ActorID | None = None
    pool: ThreadPoolExecutor | None = None
    group_pools: dict = field(default_factory=dict)  # name -> bounded pool
    group_limits: dict = field(default_factory=dict)  # name -> max
    group_sems: dict = field(default_factory=dict)   # name -> loop Semaphore
    loop = None  # asyncio loop for async actors
    lock: threading.Lock = field(default_factory=threading.Lock)
    expected_seq: dict[bytes, int] = field(default_factory=dict)
    pending: dict[bytes, dict[int, tuple]] = field(default_factory=dict)
    exiting: bool = False


class WorkerRuntime:
    def __init__(self, *, mode: str, cp_addr: tuple, agent_addr: tuple | None,
                 job_id: JobID, worker_id: WorkerID | None = None,
                 node_id: NodeID | None = None, host: str = "127.0.0.1"):
        self.mode = mode  # "driver" | "worker"
        self.job_id = job_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id = node_id
        self.cp_addr = tuple(cp_addr)
        self.agent_addr = tuple(agent_addr) if agent_addr else None
        self.peer_pool = ClientPool(f"{mode}")
        self.cp_client = RpcClient(self.cp_addr, name="cp-client")
        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter(self)
        self.reference_counter.set_on_zero(self._on_ref_zero)
        self.serialization = SerializationContext(self)
        self.function_manager = FunctionManager(self)
        self.task_manager = TaskManager(self)
        self.normal_submitter = NormalTaskSubmitter(self)
        self.actor_submitter = ActorTaskSubmitter(self)
        self.shm_client = ShmClient()
        self._ctx = _TaskContext()
        self._task_counter_lock = threading.Lock()
        self._task_counter = 0
        self._node_addr_cache: dict[NodeID, tuple] = {}
        self._actor_state = _ActorExecState()
        self._subscribed_actors: set[ActorID] = set()
        from ray_tpu.core.streaming import StreamManager
        self.stream_manager = StreamManager(self)
        self._pubsub_seen: dict[str, int] = {}  # channel -> last seq
        self._pubsub_lock = threading.Lock()
        self._pubsub_dispatch_locks: dict[str, threading.Lock] = {}
        self._pubsub_poll_started = False
        # CP pubsub epoch (changes on CP restart): the recovery poll
        # watches it and re-issues every subscription + reconciles missed
        # death events when it moves (subscriptions live only in CP memory)
        self._pubsub_epoch: str | None = None
        # node-death reconciliation state: NodeIDs we believe alive, fed by
        # "node" channel dispatches; on a CP restart, nodes that vanished
        # from the replayed table get a synthesized "dead" event
        self._known_alive_nodes: set = set()
        # app-level channel subscribers (e.g. the Serve controller watching
        # CP "node" death events); called on the dispatch thread
        self._pubsub_handlers: dict[str, list] = {}
        self._cancelled_tasks: set[TaskID] = set()
        self._device_objects: dict[ObjectID, Any] = {}  # HBM-resident values
        self._normal_exec = _NormalTaskQueue()
        self._running_tasks: dict[TaskID, threading.Event] = {}
        self._blocked_notified = threading.local()
        # Process-exit hook: worker_main's default is the real thing; the
        # in-process worker mode (scale/autoscaler test harness — the
        # fake_multi_node analog) routes it to a soft shutdown so a worker
        # "exit" cannot kill the host process.
        self.on_exit = os._exit
        # ObjectRef.__del__ enqueues here instead of calling into the
        # reference counter synchronously: destructors fire inside arbitrary
        # allocations, where the current thread may already hold framework
        # locks (GC-reentrancy self-deadlock; see object_ref.py). deque
        # append/popleft are GIL-atomic — no lock in the destructor path.
        self._release_q: deque = deque()
        # Eager: lazy init would race on the reply threads and register the
        # same Prometheus series twice (the registry doesn't dedup).
        from ray_tpu.util.metrics import Histogram
        self._latency_hist = Histogram(
            "ray_tpu_task_latency_seconds",
            "Submit-to-completion latency of tasks owned by this process",
            boundaries=[0.005, 0.02, 0.1, 0.5, 2, 10, 60, 300],
            tag_keys=("type",))
        self._shutdown = threading.Event()
        self._open_frames: set = set()  # batch frames awaiting early flush
        self._frames_lock = threading.Lock()
        self._frames_event = threading.Event()
        self._frame_janitor_started = False
        self._early_send_failures: dict[tuple, float] = {}  # addr -> ts
        self._driver_task_id = TaskID.for_driver(job_id)
        self.task_events: list[dict] = []  # flushed to CP (TaskEventBuffer)
        # span sink: finished spans batch to the CP trace store. An
        # ACKNOWLEDGED call, not a one-way notify: a send into a CP that
        # just died can "succeed" into the kernel buffer and vanish — the
        # call surfaces the failure so tracing.flush() re-queues the spans
        tracing.register_flusher(
            lambda spans: self.cp_client.call(
                "report_spans", {"spans": spans}, timeout=10.0))
        # metrics auto-flush (ISSUE 4): every worker/driver pushes delta
        # snapshots to the CP time-series store; the handle is None when a
        # co-resident component (the head process's CP) started it first.
        # Acknowledged for the same reason — an undetected drop would lose
        # the already-advanced delta baselines for good.
        self._metrics_flusher = None
        if get_config().metrics_enabled:
            from ray_tpu.util import metrics as _metrics
            self._metrics_flusher = _metrics.start_flusher(
                lambda p: self.cp_client.call("metrics_report", p,
                                              timeout=10.0),
                source=self.worker_id.hex(),
                node_id=self.node_id.hex() if self.node_id else None)
        self._server = RpcServer(
            self._handle, host=host, name=f"{mode}-rpc",
            blocking_methods={"push_task", "get_object_status", "wait_object"},
            pool_size=8)
        self.addr = self._server.addr
        if mode == "driver" and get_config().log_to_driver:
            self._subscribe_channel(f"worker_logs:{job_id.hex()}")

    # ------------------------------------------------------------------
    # identity & context
    def current_task_id(self) -> TaskID:
        return self._ctx.task_id or self._driver_task_id

    def _next_task_id(self) -> TaskID:
        with self._task_counter_lock:
            self._task_counter += 1
            c = self._task_counter
        return TaskID.for_task(self.job_id, self.current_task_id(), c)

    def in_actor(self) -> bool:
        return self._actor_state.instance is not None

    # ------------------------------------------------------------------
    # public ops: put / get / wait
    def defer_release(self, oid: ObjectID) -> None:
        """Queue a local-ref release from ObjectRef.__del__ (lock-free)."""
        self._release_q.append(oid)

    def defer_call(self, fn: Callable) -> None:
        """Queue arbitrary destructor-side cleanup (e.g. stream abandon) to
        run on a safe stack — same GC-reentrancy rules as defer_release."""
        self._release_q.append(fn)

    def drain_releases(self) -> None:
        """Apply queued __del__ releases. Called from plain API entry
        points (no framework locks held) and the pubsub poll loop, so the
        on-zero cascade (refcount → task manager → memory store → remote
        store deletes) runs on a safe stack."""
        q = self._release_q
        while True:
            try:
                item = q.popleft()
            except IndexError:
                return
            try:
                if callable(item):
                    item()
                else:
                    self.reference_counter.remove_local_ref(item)
            except Exception:  # noqa: BLE001 — release must never throw
                logger.exception("deferred release failed")

    def put(self, value: Any, *, device_hint: str = "") -> ObjectRef:
        self.drain_releases()
        self._ctx.put_counter += 1
        oid = ObjectID.for_put(self.current_task_id(), self._ctx.put_counter)
        with tracing.span("object.put", kind="object", child_only=True,
                          attrs={"object_id": oid.hex()[:16]}):
            if _is_device_array(value):
                # device-resident object (ref: experimental/gpu_object_manager):
                # the array stays in THIS process's HBM; same-process gets return
                # the live handle with no device↔host round-trip. The serialized
                # host copy below is the durable/cross-process representation
                # (chips admit one process, so crossing processes crosses the
                # host anyway — SURVEY.md §7 hard-part 7).
                self._device_objects[oid] = value
                device_hint = device_hint or "jax"
            sobj = self.serialization.serialize(value)
            self.reference_counter.add_owned(oid, contained_refs=sobj.contained_refs)
            if sobj.serialized_size() <= get_config().max_inline_object_size or self.agent_addr is None:
                self.memory_store.put_inline(oid, sobj)
            else:
                self._put_shm(oid, sobj, device_hint)
            return ObjectRef(oid, self.worker_id, self.addr)

    def _put_shm(self, oid: ObjectID, sobj: SerializedObject, device_hint: str = ""):
        size = sobj.serialized_size()
        agent = self.peer_pool.get(self.agent_addr)
        reply = agent.call_with_retry(
            "store_create",
            {"object_id": oid, "size": size, "device_hint": device_hint,
             "owner_addr": self.addr}, timeout=30.0)
        mv = self._writable_extent(reply["shm_name"], size,
                                   reply.get("offset", 0))
        _write_serialized(mv, sobj)
        agent.call_with_retry("store_seal", {"object_id": oid}, timeout=30.0)
        self.memory_store.put_location(oid, self.node_id)

    def _writable_extent(self, shm_name: str, size: int, offset: int):
        """Writable view of an arena extent. Same-process arenas (head-mode
        driver, in-proc workers) write through the agent's mapping — its
        pages are pre-materialized by the native store's background
        toucher, while a fresh client mmap pays a minor fault per 4 KiB of
        every cold extent (the difference between ~1.6 and ~6 GB/s put
        bandwidth on one core)."""
        from ray_tpu.core.object_store import local_arena
        arena = local_arena(shm_name)
        if arena is not None:
            mv = arena.local_write_view(offset, size)
            if mv is not None:
                return mv
        return self.shm_client.map(shm_name, size, offset)

    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list[Any]:
        self.drain_releases()
        watchdog = timeout is None and get_config().blocking_watchdog_s > 0
        if watchdog:
            timeout = get_config().blocking_watchdog_s
        deadline = None if timeout is None else time.monotonic() + timeout
        out: list[Any] = []
        with tracing.span("object.get", kind="object", child_only=True,
                          attrs={"num_refs": len(refs)}):
            for ref in refs:
                try:
                    out.append(self._get_one(ref, deadline))
                except GetTimeoutError:
                    if not watchdog:
                        raise
                    raise GetTimeoutError(
                        f"get() watchdog: no result after {timeout:.0f}s on "
                        f"{ref.id().hex()[:12]} — a lost reply or dead owner "
                        "would otherwise hang forever. For legitimately longer "
                        "work pass an explicit timeout or raise/disable "
                        "RAY_TPU_BLOCKING_WATCHDOG_S (0 disables).") from None
        return out

    def _remaining(self, deadline) -> float | None:
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def _get_one(self, ref: ObjectRef, deadline) -> Any:
        oid = ref.id()
        dev = self._device_objects.get(oid)
        if dev is not None:
            return dev  # same-process device-resident handle, zero-copy
        reconstruction_attempts = 3
        while True:
            if self.reference_counter.is_owned(oid) or self.memory_store.contains(oid):
                ent = self._wait_local(oid, deadline)
                if ent is None:
                    raise GetTimeoutError(f"get on {oid.hex()[:12]} timed out")
                if ent.inline is not None:
                    return self._materialize(ent.inline, ent.is_error)
                value, ok = self._read_shm(oid, ent.locations)
                if ok:
                    return value
                # all copies lost: lineage reconstruction
                if reconstruction_attempts > 0 and self.task_manager.reconstruct_object(oid):
                    reconstruction_attempts -= 1
                    continue
                raise ObjectLostError(oid.hex())
            # borrowed: ask the owner
            status = self._owner_status(ref, deadline, wait=True)
            if status is None:
                raise GetTimeoutError(f"get on {oid.hex()[:12]} timed out (owner)")
            kind = status.get("kind")
            if kind == "inline":
                return self._materialize(
                    SerializedObject.from_buffer(status["data"]), status.get("is_error", False))
            if kind == "shm":
                self.memory_store.put_location(oid, status["node_id"])
                value, ok = self._read_shm(oid, [status["node_id"]], owner_addr=ref.owner_addr)
                if ok:
                    return value
                self.memory_store.remove_location(oid, status["node_id"])
                continue
            if kind == "lost":
                raise ObjectLostError(oid.hex())
            time.sleep(0.005)
            if deadline is not None and time.monotonic() > deadline:
                raise GetTimeoutError(f"get on {oid.hex()[:12]} timed out")

    def _wait_local(self, oid: ObjectID, deadline):
        ent = self.memory_store.get(oid)
        if ent is not None:
            return ent
        self._notify_blocked()
        self._normal_exec.on_blocked()
        try:
            return self.memory_store.wait_for(oid, self._remaining(deadline))
        finally:
            self._normal_exec.on_unblocked()

    def yield_exec_slot(self):
        """Context manager for API-level blocking waits (named-actor
        resolution, PG readiness): lets the next queued pipelined task run
        on this worker while we block — the same slot-yield get()/wait()
        do internally. Fully a no-op outside a normal-task runner thread
        (actor executor threads must NOT release their lease's CPU here:
        worker_blocked has no re-acquire path)."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            if self._normal_exec.is_runner():
                self._notify_blocked()
                self._normal_exec.on_blocked()
                try:
                    yield
                finally:
                    self._normal_exec.on_unblocked()
            else:
                yield
        return cm()

    def _notify_blocked(self):
        """Release our CPU while blocked so nested tasks can schedule
        (ref: raylet blocked-worker release)."""
        if self.mode != "worker" or self.agent_addr is None:
            return
        if getattr(self._blocked_notified, "sent", False):
            return
        self._blocked_notified.sent = True
        try:
            # advisory CPU-release hint; a lost one costs one idle slot
            # until the worker unblocks, never correctness
            # graftlint: fire-and-forget
            self.peer_pool.get(self.agent_addr).notify(
                "worker_blocked", {"worker_id": self.worker_id})
        except Exception:
            pass

    def _materialize(self, sobj: SerializedObject, is_error: bool) -> Any:
        value = self.serialization.deserialize(sobj)
        if is_error:
            raise value if isinstance(value, BaseException) else TaskError(formatted=str(value))
        return value

    def _read_shm(self, oid: ObjectID, locations, owner_addr=None) -> tuple[Any, bool]:
        if self.agent_addr is None:
            return None, False
        agent = self.peer_pool.get(self.agent_addr)
        try:
            meta = agent.call_with_retry(
                "store_get_meta", {"object_id": oid}, timeout=30.0)
        except ObjectStoreFullError:
            # the meta fetch can RESTORE a spilled object; under transient
            # pressure that can fail — back off, let the get loop re-poll
            time.sleep(0.2)
            return None, False
        if meta is None:
            # not local: pull from a remote holder (ref: pull_manager.h:49)
            for node_id in list(locations or []):
                if node_id == self.node_id:
                    continue
                remote_addr = self._node_addr(node_id)
                if remote_addr is None:
                    continue
                try:
                    r = agent.call_with_retry(
                        "pull_object",
                        {"object_id": oid, "from_addr": remote_addr,
                         "owner_addr": owner_addr},
                        timeout=120.0)
                except ObjectStoreFullError:
                    # destination store momentarily full of UNSEALED inbound
                    # chunks (nothing spillable): back off and let the
                    # caller's get loop re-poll — pressure resolves as
                    # in-flight transfers seal and consumers release
                    # (reference: plasma blocks creates under pressure)
                    time.sleep(0.2)
                    return None, False
                if r.get("ok"):
                    try:
                        meta = agent.call_with_retry(
                            "store_get_meta", {"object_id": oid},
                            timeout=30.0)
                    except ObjectStoreFullError:
                        # the freshly pulled copy was spilled and its
                        # restore hit pressure: back off and re-poll
                        time.sleep(0.2)
                        return None, False
                    break
            if meta is None:
                return None, False
        shm_name, offset, size, _device = meta[:4]
        copy_on_read = bool(meta[4]) if len(meta) > 4 else False
        try:
            mv = self.shm_client.map(shm_name, size, offset)
            if copy_on_read:
                # arena-backed extents are reused after eviction;
                # deserialized buffers must not alias the mapping (see
                # NativeShmStore.get_meta)
                mv = memoryview(bytes(mv))
            sobj = SerializedObject.from_buffer(mv)
            return self.serialization.deserialize(sobj), True
        finally:
            # Release the read lease get_meta took. Until this, the store
            # must not spill/delete the extent: an overwrite during the
            # copy-out hands the deserializer a TORN buffer, and arrow's
            # IPC parser segfaults on corrupt bytes (observed in dmesg).
            # Arena extents are copy_on_read, python-backend segments stay
            # valid while mapped — so after deserialize the lease can drop.
            try:
                # lease-release hint; store leases expire on their own TTL
                # graftlint: fire-and-forget
                agent.notify("store_read_done", {"object_id": oid})
            except Exception:  # noqa: BLE001
                pass

    def _node_addr(self, node_id: NodeID):
        addr = self._node_addr_cache.get(node_id)
        if addr is not None:
            return addr
        try:
            nodes = self.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
        except Exception:
            return None
        for n in nodes:
            self._node_addr_cache[n["node_id"]] = tuple(n["addr"])
        return self._node_addr_cache.get(node_id)

    def _owner_status(self, ref: ObjectRef, deadline, wait: bool):
        owner_addr = ref.owner_addr
        if owner_addr is None:
            return None
        t = self._remaining(deadline)
        body = {"object_id": ref.id(), "wait": wait,
                "timeout": min(t, 5.0) if t is not None else 5.0}
        try:
            if wait:
                self._notify_blocked()
                self._normal_exec.on_blocked()
            try:
                return self.peer_pool.get(owner_addr).call_with_retry(
                    "get_object_status", body,
                    timeout=(body["timeout"] + 10.0))
            finally:
                if wait:
                    self._normal_exec.on_unblocked()
        except Exception as e:
            return {"kind": "lost", "error": str(e)}

    def is_ready(self, ref: ObjectRef) -> bool:
        oid = ref.id()
        if self.memory_store.contains(oid):
            return True
        if self.reference_counter.is_owned(oid):
            return False
        status = self._owner_status(ref, None, wait=False)
        if status and status.get("kind") in ("inline", "shm"):
            if status.get("kind") == "shm":
                self.memory_store.put_location(oid, status["node_id"])
            elif status.get("kind") == "inline":
                self.memory_store.put_inline(
                    oid, SerializedObject.from_buffer(status["data"]),
                    status.get("is_error", False))
            return True
        return False

    def wait(self, refs: list[ObjectRef], num_returns: int = 1,
             timeout: float | None = None) -> tuple[list[ObjectRef], list[ObjectRef]]:
        """Event-driven wait (ref: CoreWorker::Wait core_worker.h:695 + the
        raylet's WaitManager): owned refs wake on memory-store availability,
        borrowed refs on owner long-poll replies — no per-ref poll loop."""
        self.drain_releases()
        watchdog = timeout is None and get_config().blocking_watchdog_s > 0
        if watchdog:
            timeout = get_config().blocking_watchdog_s
        deadline = None if timeout is None else time.monotonic() + timeout
        cond = threading.Condition()
        ready_ids: set = set()
        finished = [False]
        cleanups: list = []

        def mark(oid):
            with cond:
                ready_ids.add(oid)
                cond.notify_all()

        need_block = False
        for ref in refs:
            oid = ref.id()
            if self.memory_store.contains(oid):
                ready_ids.add(oid)
            elif self.reference_counter.is_owned(oid):
                cb = (lambda ent, o=oid: mark(o))
                self.memory_store.on_available(oid, cb)
                cleanups.append((oid, cb))
                need_block = True
            else:
                self._owner_wait_async(ref, mark, finished, deadline)
                need_block = True

        if need_block and len(ready_ids) < num_returns:
            self._notify_blocked()
        self._normal_exec.on_blocked()
        try:
            with cond:
                cond.wait_for(
                    lambda: len(ready_ids) >= min(num_returns, len(refs)),
                    self._remaining(deadline))
                finished[0] = True
                ready_now = set(ready_ids)
        finally:
            self._normal_exec.on_unblocked()
        for oid, cb in cleanups:
            self.memory_store.remove_callback(oid, cb)
        if watchdog and len(ready_now) < min(num_returns, len(refs)):
            raise GetTimeoutError(
                f"wait() watchdog: {len(ready_now)}/{min(num_returns, len(refs))} "
                f"refs ready after {timeout:.0f}s with no explicit timeout — "
                "a lost reply or dead owner would otherwise hang forever. For "
                "legitimately longer work pass an explicit timeout or "
                "raise/disable RAY_TPU_BLOCKING_WATCHDOG_S (0 disables).")
        ready = [r for r in refs if r.id() in ready_now]
        if len(ready) > num_returns:
            ready = ready[:num_returns]
        ready_set = {id(r) for r in ready}
        return ready, [r for r in refs if id(r) not in ready_set]

    def _owner_wait_async(self, ref: ObjectRef, mark, finished, deadline):
        """Long-poll the owner for a borrowed ref's status; re-arms itself on
        'pending' replies until the wait finishes (event-driven borrower side
        of get_object_status, ref: core_worker.proto:492).

        Transport failures re-arm with backoff rather than abandoning the
        ref: one dropped RPC to a live owner must not turn a blocking wait
        into a permanent hang. Only an explicit 'lost' status gives up."""
        owner_addr = ref.owner_addr
        oid = ref.id()
        if owner_addr is None:
            return
        backoff = [0.05]

        def retry_later():
            if finished[0]:
                return
            if deadline is not None and time.monotonic() >= deadline:
                return
            delay = backoff[0]
            backoff[0] = min(delay * 2, 2.0)
            t = threading.Timer(delay, issue)
            t.daemon = True
            t.start()

        def on_reply(ok, status):
            if finished[0]:
                return
            if ok and isinstance(status, dict):
                backoff[0] = 0.05  # owner is healthy
                kind = status.get("kind")
                if kind == "shm":
                    self.memory_store.put_location(oid, status["node_id"])
                    mark(oid)
                    return
                if kind == "inline":
                    self.memory_store.put_inline(
                        oid, SerializedObject.from_buffer(status["data"]),
                        status.get("is_error", False))
                    mark(oid)
                    return
                if kind == "lost":
                    return  # never becomes ready
            elif not ok:
                retry_later()  # transient transport failure: re-arm
                return
            if deadline is not None and time.monotonic() >= deadline:
                return
            issue()

        def issue():
            if finished[0]:
                return
            t = self._remaining(deadline)
            body = {"object_id": oid, "wait": True,
                    "timeout": min(t, 5.0) if t is not None else 5.0}
            try:
                self.peer_pool.get(owner_addr).call_async(
                    "get_object_status", body, callback=on_reply)
            except Exception:
                retry_later()

        issue()

    # ------------------------------------------------------------------
    # task submission
    def submit_task(self, fn: Callable, args: tuple, kwargs: dict, *,
                    num_returns: int | str = 1, resources: dict | None = None,
                    strategy: SchedulingStrategy | None = None,
                    max_retries: int | None = None, retry_exceptions: bool = False,
                    name: str = "", runtime_env: dict | None = None):
        self.drain_releases()
        cfg = get_config()
        if runtime_env:
            from ray_tpu.runtime_env import prepare_runtime_env
            runtime_env = prepare_runtime_env(self, runtime_env)
        streaming = num_returns == "streaming"
        spec = TaskSpec(
            task_id=self._next_task_id(), job_id=self.job_id,
            task_type=TaskType.NORMAL, name=name or getattr(fn, "__name__", "task"),
            function_id=self.function_manager.export(fn),
            args=self._serialize_args(args, kwargs),
            num_returns=0 if streaming else num_returns,
            streaming=streaming, resources=resources or {"CPU": 1.0},
            strategy=strategy or DefaultStrategy(),
            max_retries=cfg.task_max_retries if max_retries is None else max_retries,
            retry_exceptions=retry_exceptions, runtime_env=runtime_env,
            owner_id=self.worker_id, owner_addr=self.addr,
            caller_id=self.worker_id, depth=self._depth() + 1)
        with tracing.span(f"task.submit:{spec.name}", kind="submit",
                          attrs={"task_id": spec.task_id.hex()[:16]}):
            spec.trace_ctx = tracing.inject()
            spec.deadline = request_deadline.current()
            refs = self._register_returns(spec)
            gen = self.stream_manager.register(spec) if streaming else None
            self.task_manager.add_pending(spec)
            self._record_task_event(spec, "SUBMITTED")
            self.normal_submitter.submit(spec)
        return gen if streaming else refs

    def submit_actor_creation(self, cls, args: tuple, kwargs: dict, *, actor_id: ActorID,
                              resources: dict | None = None, name: str = "",
                              detached: bool = False, max_restarts: int = 0,
                              max_task_retries: int = 0, max_concurrency: int = 1,
                              is_async: bool = False,
                              strategy: SchedulingStrategy | None = None,
                              runtime_env: dict | None = None,
                              concurrency_groups: dict | None = None) -> None:
        if runtime_env:
            from ray_tpu.runtime_env import prepare_runtime_env
            runtime_env = prepare_runtime_env(self, runtime_env)
        spec = TaskSpec(
            task_id=self._next_task_id(), job_id=self.job_id,
            task_type=TaskType.ACTOR_CREATION, name=cls.__name__,
            function_id=self.function_manager.export(cls),
            args=self._serialize_args(args, kwargs),
            num_returns=0, resources=resources or {"CPU": 1.0},
            strategy=strategy or DefaultStrategy(),
            owner_id=self.worker_id, owner_addr=self.addr,
            actor_id=actor_id, max_restarts=max_restarts,
            max_task_retries=max_task_retries, max_concurrency=max_concurrency,
            is_async_actor=is_async, caller_id=self.worker_id,
            runtime_env=runtime_env, concurrency_groups=concurrency_groups)
        with tracing.span(f"actor.create:{spec.name}", kind="submit",
                          attrs={"actor_id": actor_id.hex()[:16]}):
            spec.trace_ctx = tracing.inject()
            self.cp_client.call_with_retry(
                "create_actor",
                {"spec": spec, "name": name, "detached": detached},
                timeout=60.0)

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args: tuple,
                          kwargs: dict, *, num_returns: int | str = 1,
                          max_task_retries: int = 0, name: str = "",
                          concurrency_group: str = ""):
        self.drain_releases()
        streaming = num_returns == "streaming"
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(self.job_id, actor_id, self._bump_counter()),
            job_id=self.job_id, task_type=TaskType.ACTOR_TASK,
            name=name, method_name=method_name,
            args=self._serialize_args(args, kwargs),
            num_returns=0 if streaming else num_returns,
            streaming=streaming, resources={},
            max_retries=max_task_retries,
            owner_id=self.worker_id, owner_addr=self.addr,
            actor_id=actor_id, caller_id=self.worker_id,
            concurrency_group=concurrency_group)
        with tracing.span(f"actor.submit:{spec.name or spec.method_name}",
                          kind="submit",
                          attrs={"task_id": spec.task_id.hex()[:16],
                                 "actor_id": actor_id.hex()[:16]}):
            spec.trace_ctx = tracing.inject()
            spec.deadline = request_deadline.current()
            refs = self._register_returns(spec)
            gen = self.stream_manager.register(spec) if streaming else None
            self.task_manager.add_pending(spec)
            self._record_task_event(spec, "SUBMITTED")
            self.actor_submitter.submit(spec)
        return gen if streaming else refs

    def _bump_counter(self) -> int:
        with self._task_counter_lock:
            self._task_counter += 1
            return self._task_counter

    def _depth(self) -> int:
        return 0

    def resubmit_spec(self, spec: TaskSpec):
        if spec.task_type == TaskType.ACTOR_TASK:
            self.actor_submitter.submit(spec)
        else:
            self.normal_submitter.submit(spec)

    def _serialize_args(self, args: tuple, kwargs: dict) -> list[TaskArg]:
        out: list[TaskArg] = []
        cfg = get_config()
        for key, value in [(None, a) for a in args] + list(kwargs.items()):
            if isinstance(value, ObjectRef):
                self.reference_counter.add_task_dep(value.id(), value.owner_addr)
                out.append(TaskArg(is_ref=True,
                                   ref=(value.id(), value.owner, value.owner_addr,
                                        key)))
                continue
            sobj = self.serialization.serialize(value)
            if sobj.serialized_size() > cfg.max_inline_object_size and self.agent_addr is not None:
                ref = self.put(value)
                self.reference_counter.add_task_dep(ref.id(), ref.owner_addr)
                out.append(TaskArg(is_ref=True,
                                   ref=(ref.id(), ref.owner, ref.owner_addr, key),
                                   contained=[ref]))
                continue
            # .contained carries the kwarg name (None = positional); nested refs
            # inside the value travel via the serializer's borrow protocol.
            out.append(TaskArg(is_ref=False, data=sobj.to_bytes(), contained=[key]))
        return out

    def _register_returns(self, spec: TaskSpec) -> list[ObjectRef]:
        refs = []
        for oid in spec.return_ids():
            self.reference_counter.add_owned(oid)
            refs.append(ObjectRef(oid, self.worker_id, self.addr))
        return refs

    # ------------------------------------------------------------------
    # reply processing (owner side)
    def process_task_reply(self, spec: TaskSpec, reply: dict):
        # Atomically claim this reply: late/duplicate copies (a task already
        # completed, cancelled, failed via actor death, superseded by a
        # retry attempt — or a batch frame's early reply racing the frame's
        # aggregate copy) must not double-release deps or overwrite the
        # recorded result (ref: task_manager.cc attempt-number checks).
        claimed = self.task_manager.claim_reply(
            spec.task_id, reply.get("attempt", spec.attempt_number))
        if claimed is None:
            return
        if reply.get("error"):
            self.fail_task(spec, TaskError(formatted=str(reply["error"]),
                                           task_repr=spec.repr_name()),
                           _already_claimed=True)
            return
        if reply.get("app_error"):
            # streaming task raised with retry_exceptions: re-run the whole
            # generator, or fail the stream once retries are exhausted
            retry = self.task_manager.should_retry_app_error(spec.task_id)
            if retry is not None:
                logger.info("retrying streaming task %s after application "
                            "error", spec.repr_name())
                self.resubmit_spec(retry)
                return
            err = self.serialization.deserialize(
                SerializedObject.from_buffer(reply["app_error"]))
            self.fail_task(spec, err if isinstance(err, TaskError)
                           else TaskError(err, task_repr=spec.repr_name()),
                           _already_claimed=True)
            return
        results = reply.get("results", [])
        if any(is_err for (_, _, _, is_err) in results):
            retry = self.task_manager.should_retry_app_error(spec.task_id)
            if retry is not None:
                logger.info("retrying task %s after application error", spec.repr_name())
                self.resubmit_spec(retry)
                return
        for oid, kind, data, is_error in results:
            if kind == "inline":
                self.memory_store.put_inline(
                    oid, SerializedObject.from_buffer(data), is_error)
            else:
                self.memory_store.put_location(oid, data)
        self._release_deps(spec)
        elapsed = self.task_manager.complete(spec.task_id)
        self._observe_latency(spec, elapsed)
        self._record_task_event(spec, "FINISHED")

    def fail_task(self, spec: TaskSpec, error: TaskError,
                  _already_claimed: bool = False):
        # already completed/failed, or a reply is being processed right now:
        # don't double-release deps (claim_reply is the atomic arbiter)
        if not _already_claimed and \
                self.task_manager.claim_reply(spec.task_id, None) is None:
            return
        sobj = self.serialization.serialize(error)
        for oid in spec.return_ids():
            self.memory_store.put_inline(oid, sobj, is_error=True)
        if spec.streaming:
            # consumers blocked in next() must observe the failure
            self.stream_manager.fail(spec, sobj)
        self._release_deps(spec)
        elapsed = self.task_manager.complete(spec.task_id)
        self._observe_latency(spec, elapsed)
        self._record_task_event(spec, "FAILED")

    def _release_deps(self, spec: TaskSpec):
        for a in spec.args:
            if a.is_ref:
                self.reference_counter.remove_task_dep(a.ref[0], a.ref[2])

    def _observe_latency(self, spec: TaskSpec, elapsed: float | None):
        """Owner-side submit→finish latency histogram (ref: the dashboard's
        task-latency metrics; would localize a slow/wedged call path in one
        /metrics scrape)."""
        if elapsed is not None:
            self._latency_hist.observe(elapsed,
                                       {"type": spec.task_type.name})

    def _on_ref_zero(self, oid: ObjectID):
        """Owned count hit zero: drop the value everywhere
        (ref: reference_count.cc delete path)."""
        self._device_objects.pop(oid, None)
        ent = self.memory_store.get(oid)
        self.memory_store.delete(oid)
        self.task_manager.release_lineage(oid)
        if ent is not None and ent.locations:
            for node_id in ent.locations:
                addr = self.agent_addr if node_id == self.node_id else self._node_addr(node_id)
                if addr is not None:
                    try:
                        # best-effort eager free; agent-side eviction
                        # reclaims anything a lost delete leaves behind
                        # graftlint: fire-and-forget
                        self.peer_pool.get(addr).notify("store_delete", {"object_id": oid})
                    except Exception:
                        pass

    def _record_task_event(self, spec: TaskSpec, state: str):
        self.task_events.append({
            "task_id": spec.task_id.hex(), "name": spec.repr_name(),
            "state": state, "ts": time.time(), "attempt": spec.attempt_number,
            "worker_id": self.worker_id.hex(), "job_id": spec.job_id.hex(),
            "type": spec.task_type.name,
        })
        if len(self.task_events) >= 512:
            self.flush_task_events()

    def flush_task_events(self):
        events, self.task_events = self.task_events, []
        if not events:
            return
        try:
            # observability sink — losing a batch degrades the task-events
            # timeline, never execution
            # graftlint: fire-and-forget
            self.cp_client.notify("report_task_events", {"events": events})
        except Exception:
            pass

    # ------------------------------------------------------------------
    # RPC handlers (executor side)
    def _handle(self, method: str, body, peer):
        fn = getattr(self, "_h_" + method, None)
        if fn is None:
            raise ValueError(f"worker: unknown method {method}")
        return fn(body)

    def _h_ping(self, body):
        # worker_id lets borrow-probing owners detect a reused port
        return {"ok": True, "worker_id": self.worker_id.hex()}

    def _h_dump_stacks(self, body):
        """Every thread's Python stack, on demand (ref: the dashboard's
        py-spy/profile endpoints, dashboard/modules/reporter/
        profile_manager.py:191 — this is how a wedged worker gets
        diagnosed without attaching a debugger)."""
        from ray_tpu.observability.profiling import dump_thread_stacks
        return {"worker_id": self.worker_id.hex(), "pid": os.getpid(),
                "stacks": dump_thread_stacks()}

    def _h_profiling_start(self, body):
        """Begin an XPlane (jax.profiler) capture in THIS process — the
        leaf of the cluster-wide `ray-tpu profile` fan-out (CP → node
        agent → worker). One capture per process at a time; a concurrent
        start reports the error instead of corrupting the active run."""
        from ray_tpu.observability import profiling
        try:
            info = profiling.start_capture((body or {}).get("logdir"))
            return {"ok": True, "worker_id": self.worker_id.hex(), **info}
        except Exception as e:  # noqa: BLE001 - report, don't kill the RPC
            return {"ok": False, "worker_id": self.worker_id.hex(),
                    "pid": os.getpid(), "error": repr(e)}

    def _h_profiling_stop(self, body):
        """End the active XPlane capture; returns the trace logdir (the
        artifact the CP registers and the dashboard serves)."""
        from ray_tpu.observability import profiling
        try:
            info = profiling.stop_capture()
            return {"ok": True, "worker_id": self.worker_id.hex(), **info}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "worker_id": self.worker_id.hex(),
                    "pid": os.getpid(), "error": repr(e)}

    def _h_save_device_memory_profile(self, body):
        """Dump this process's device (HBM) memory profile — the remote
        'why is replica 3 OOMing' tool."""
        from ray_tpu.observability import profiling
        try:
            path = profiling.save_device_memory_profile(
                (body or {}).get("path"))
            return {"ok": True, "worker_id": self.worker_id.hex(),
                    "pid": os.getpid(), "path": path}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "worker_id": self.worker_id.hex(),
                    "pid": os.getpid(), "error": repr(e)}

    def _h_inc_borrow(self, body):
        if isinstance(body, dict):
            self.reference_counter.inc_borrow(
                body["object_id"], body.get("holder"))
        else:
            self.reference_counter.inc_borrow(body)
        return {"ok": True}

    def _h_attach_borrow(self, body):
        self.reference_counter.attach_borrow(
            body["object_id"], body["holder"])
        return {"ok": True}

    def _h_dec_borrow(self, body):
        if isinstance(body, dict):
            self.reference_counter.dec_borrow(
                body["object_id"], body.get("holder"))
        else:
            self.reference_counter.dec_borrow(body)
        return {"ok": True}

    def _h_get_object_status(self, body):
        """Owner-side status/fetch (ref: core_worker.proto:492 GetObjectStatus)."""
        oid: ObjectID = body["object_id"]
        ent = self.memory_store.get(oid)
        if ent is None and body.get("wait"):
            ent = self.memory_store.wait_for(oid, body.get("timeout", 5.0))
        if ent is None:
            if (not self.reference_counter.is_owned(oid)
                    and not self.task_manager.get_pending_spec(oid.task_id())):
                return {"kind": "lost"}
            return {"kind": "pending"}
        if ent.inline is not None:
            return {"kind": "inline", "data": ent.inline.to_bytes(),
                    "is_error": ent.is_error}
        if ent.locations:
            return {"kind": "shm", "node_id": ent.locations[0]}
        return {"kind": "pending"}

    def _h_object_lost(self, body):
        """A node evicted/lost our primary copy (ref: object_recovery_manager)."""
        oid = body["object_id"]
        self.memory_store.remove_location(oid, body["node_id"])
        if (self.reference_counter.is_owned(oid)
                and get_config().enable_object_reconstruction
                and not self.memory_store.contains(oid)):
            self.task_manager.reconstruct_object(oid)
        return {"ok": True}

    def _h_object_moved(self, body):
        """A draining node re-homed our primary copy to a survivor: add the
        new location FIRST, then retire the old one — the reverse order
        would leave a window with no locations where a racing get falls
        back to lineage reconstruction for an object that still exists."""
        oid = body["object_id"]
        self.memory_store.put_location(oid, body["node_id"])
        if body.get("from_node_id") is not None:
            self.memory_store.remove_location(oid, body["from_node_id"])
        return {"ok": True}

    def _h_pubsub(self, body):
        channel, msg = body["channel"], body["msg"]
        if isinstance(msg, dict) and "__seq" in msg:
            # seq-enveloped push (CP also logs it for long-poll recovery).
            # The watermark only advances CONTIGUOUSLY: if push N was lost
            # and N+1 arrives, dispatching N+1 and advancing would make the
            # poll skip N forever — instead the gapped push is dropped and
            # the recovery poll replays N, N+1 in order.
            # The watermark advance + dispatch are atomic per channel (the
            # ordering lock): otherwise the push thread (msg N+1) and the
            # recovery-poll thread (msg N) could dispatch concurrently and
            # apply state transitions out of seq order (e.g. an actor
            # ALIVE processed after its later DEAD).
            seq, msg = msg["__seq"], msg["payload"]
            with self._pubsub_order_lock(channel):
                with self._pubsub_lock:
                    seen = self._pubsub_seen.get(channel, 0)
                    if seq != seen + 1:
                        return {"ok": True}  # stale/gapped (poll recovers)
                    self._pubsub_seen[channel] = seq
                return self._dispatch_pubsub(channel, msg)
        return self._dispatch_pubsub(channel, msg)

    def _pubsub_order_lock(self, channel: str) -> threading.Lock:
        """Per-channel lock serializing watermark-advance + dispatch so
        message application follows sequence order across the push and
        recovery-poll threads."""
        with self._pubsub_lock:
            lock = self._pubsub_dispatch_locks.get(channel)
            if lock is None:
                lock = self._pubsub_dispatch_locks[channel] = threading.Lock()
            return lock

    def register_pubsub_handler(self, channel: str, callback) -> None:
        """Subscribe `callback(msg)` to a CP pubsub channel (push + long-poll
        recovery). Used by in-worker subsystems — the Serve controller wires
        CP "node" death events into proactive replica replacement."""
        with self._pubsub_lock:
            self._pubsub_handlers.setdefault(channel, []).append(callback)
        self._subscribe_channel(channel)

    def _dispatch_pubsub(self, channel: str, msg):
        if channel == "node" and isinstance(msg, dict):
            # liveness bookkeeping for CP-restart reconciliation: what we
            # have heard is what we can detect going silent
            nid = msg.get("node_id")
            if nid is not None:
                with self._pubsub_lock:
                    if msg.get("event") == "alive":
                        self._known_alive_nodes.add(nid)
                    elif msg.get("event") == "dead":
                        self._known_alive_nodes.discard(nid)
        with self._pubsub_lock:
            handlers = list(self._pubsub_handlers.get(channel, ()))
        for cb in handlers:
            try:
                cb(msg)
            except Exception:  # noqa: BLE001 — app handler must not break pubsub
                logger.exception("pubsub handler failed for %s", channel)
        if channel.startswith("worker_logs:"):
            # log monitor fan-in: print worker output at the driver with a
            # provenance prefix (ref: _private/log_monitor.py + worker.py
            # print_to_stdstream)
            who = f"pid={msg.get('pid')}, node={msg.get('node_id')}"
            if msg.get("actor"):
                who = f"actor={msg['actor']}, " + who
            stream = sys.stderr if msg.get("stream") == "err" else sys.stdout
            for line in msg.get("lines", ()):
                print(f"({who}) {line}", file=stream)
            return {"ok": True}
        if channel.startswith("actor:"):
            actor_id = ActorID(bytes.fromhex(channel.split(":", 1)[1]))
            if msg.get("state") == "DEAD":
                self.actor_submitter.on_actor_death(actor_id, msg.get("reason", ""))
                # stop polling a channel that will never speak again
                with self._pubsub_lock:
                    self._pubsub_seen.pop(channel, None)
                self._subscribed_actors.discard(actor_id)
                try:
                    # CP strike-GC reaps subscriptions whose pushes keep
                    # failing, so a lost unsubscribe self-heals
                    # graftlint: fire-and-forget
                    self.cp_client.notify("unsubscribe",
                                          {"channel": channel,
                                           "addr": self.addr})
                except Exception:
                    pass
            elif msg.get("state") in ("RESTARTING", "ALIVE"):
                self.actor_submitter.on_actor_restart(actor_id)
        return {"ok": True}

    def subscribe_actor_events(self, actor_id: ActorID):
        if actor_id in self._subscribed_actors:
            return
        self._subscribed_actors.add(actor_id)
        self._subscribe_channel(f"actor:{actor_id.hex()}")

    def _subscribe_channel(self, channel: str) -> None:
        """Register for push delivery AND seed the long-poll recovery loop
        (at-least-once: pushes are best-effort; the poll replays anything
        missed, dedup'd by sequence number — ref: pubsub long-poll,
        pubsub.proto:224)."""
        try:
            # short + no retries: runtime construction must not stall on a
            # slow CP; a failed registration still seeds the recovery loop
            # (seeded at 0 -> the poll replays the channel's recent history)
            reply = self.cp_client.call(
                "subscribe", {"channel": channel, "addr": self.addr},
                timeout=2.0)
        except Exception:
            reply = None
        with self._pubsub_lock:
            self._pubsub_seen.setdefault(
                channel, (reply or {}).get("seq", 0))
            if reply and reply.get("epoch") and self._pubsub_epoch is None:
                self._pubsub_epoch = reply["epoch"]
            start = not self._pubsub_poll_started
            self._pubsub_poll_started = True
        if channel == "node" and not self._known_alive_nodes:
            # seed liveness bookkeeping with the current membership —
            # nodes that pre-date this subscription must also be
            # reconcilable after a CP restart
            try:
                nodes = self.cp_client.call("get_nodes", None, timeout=2.0)
                with self._pubsub_lock:
                    self._known_alive_nodes.update(
                        n["node_id"] for n in nodes or () if n["alive"])
            except Exception:  # noqa: BLE001 - events will fill it in
                pass
        if start:
            threading.Thread(target=self._pubsub_recovery_loop,
                             name=f"{self.mode}-pubsub-poll",
                             daemon=True).start()

    def _pubsub_recovery_loop(self):
        while not self._shutdown.is_set():
            self.drain_releases()  # idle processes still free refs promptly
            with self._pubsub_lock:
                channels = dict(self._pubsub_seen)
            if not channels:
                time.sleep(1.0)
                continue
            try:
                out = self.cp_client.call(
                    "pubsub_poll", {"channels": channels, "timeout": 30.0},
                    timeout=45.0)
            except Exception:
                time.sleep(1.0)
                continue
            out = dict(out or {})
            epoch = out.pop("__epoch", None)
            if epoch is not None:
                with self._pubsub_lock:
                    first = self._pubsub_epoch is None
                    changed = (not first) and epoch != self._pubsub_epoch
                    if first:
                        self._pubsub_epoch = epoch
                if changed:
                    # the CP restarted: all our subscriptions and the old
                    # seq numbering are gone. Re-subscribe everything,
                    # rewind watermarks, reconcile missed deaths — then
                    # poll again from scratch (`out` predates the rewind).
                    self._on_cp_restarted(epoch)
                    continue
            for channel, entries in out.items():
                for seq, msg in sorted(entries):
                    with self._pubsub_order_lock(channel):
                        with self._pubsub_lock:
                            if seq <= self._pubsub_seen.get(channel, 0):
                                continue
                            self._pubsub_seen[channel] = seq
                        try:
                            self._dispatch_pubsub(channel, msg)
                        except Exception:  # noqa: BLE001 keep the loop alive
                            logger.exception("pubsub recovery dispatch failed")

    def _on_cp_restarted(self, epoch: str) -> None:
        """The pubsub epoch moved: the CP restarted and forgot every
        subscription (they live only in CP memory) and every channel's
        sequence numbering. Re-issue all subscriptions, rewind the poll
        watermarks to 0 (the new CP's bounded log replays in full), and
        reconcile death events that happened while the CP was down: a node
        or actor that died mid-outage published nothing we could hear, so
        its absence from the replayed tables IS the death notification."""
        with self._pubsub_lock:
            self._pubsub_epoch = epoch
            channels = list(self._pubsub_seen)
        logger.info("control plane restarted (pubsub epoch %s): "
                    "re-subscribing %d channel(s)", epoch[:8], len(channels))
        for channel in channels:
            try:
                self.cp_client.call(
                    "subscribe", {"channel": channel, "addr": self.addr},
                    timeout=2.0)
            except Exception:  # noqa: BLE001 - next epoch check retries
                pass
            with self._pubsub_lock:
                if channel in self._pubsub_seen:
                    self._pubsub_seen[channel] = 0
        self._reconcile_missed_deaths()

    def _reconcile_missed_deaths(self) -> None:
        """Synthesize the death events a CP outage swallowed, from the
        replayed tables: nodes we believed alive that are gone or not
        alive in get_nodes, and subscribed actors the replayed actor table
        reports DEAD. Synthetic events flow through the normal dispatch
        path, so serve controllers/submitters react exactly as if the
        original publish had arrived."""
        with self._pubsub_lock:
            watch_nodes = "node" in self._pubsub_seen
            known = set(self._known_alive_nodes)
        if watch_nodes and known:
            try:
                nodes = self.cp_client.call("get_nodes", None, timeout=5.0)
            except Exception:  # noqa: BLE001 - reconcile on next restart
                nodes = None
            if nodes is not None:
                alive = {n["node_id"] for n in nodes if n["alive"]}
                for nid in known - alive:
                    logger.info("reconciled missed node death: %s",
                                nid.hex()[:8])
                    self._dispatch_pubsub(
                        "node", {"event": "dead", "node_id": nid})
        doomed = []
        if self._subscribed_actors:
            try:
                actors = self.cp_client.call("list_actors", None,
                                             timeout=5.0)
            except Exception:  # noqa: BLE001
                actors = None
            if actors is not None:
                states = {a["actor_id"]: a for a in actors}
                for aid in list(self._subscribed_actors):
                    info = states.get(aid)
                    if info is not None and info.get("state") == "DEAD":
                        doomed.append((aid, info.get("death_cause") or
                                       "died during control plane outage"))
        for aid, reason in doomed:
            self._dispatch_pubsub(f"actor:{aid.hex()}",
                                  {"state": "DEAD", "reason": reason})

    def _h_cancel_task(self, body):
        """(ref: core_worker.proto:540 CancelTask)"""
        tid: TaskID = body["task_id"]
        self._cancelled_tasks.add(tid)
        return {"ok": True}

    def _h_kill_actor(self, body):
        """(ref: core_worker.proto:536 KillActor). Guarded by actor id: a
        TCP port can be reused by a freshly spawned worker moments after an
        actor's worker exits, and an unguarded kill would take out the
        innocent new tenant mid-task."""
        target = body.get("actor_id")
        mine = self._actor_state.actor_id
        if target is not None and mine is not None and target != mine:
            return {"ok": False, "reason": "actor not hosted here"}
        if target is not None and mine is None:
            return {"ok": False, "reason": "no actor in this worker"}
        # reject pushes that race the exit window — a call arriving between
        # kill and process exit must fail with actor-death, not execute
        self._actor_state.exiting = True
        threading.Thread(target=self._exit_now, args=(1,),
                         daemon=True).start()
        return {"ok": True}

    def _exit_now(self, code: int):
        time.sleep(0.05)
        try:  # return held task leases so the agent's resources don't leak
            self.normal_submitter.shutdown()
        except Exception:
            pass
        self.on_exit(code)

    def _h_exit_worker(self, body):
        """Same port-reuse guard as kill_actor."""
        target = body.get("worker_id")
        if target is not None and target != self.worker_id:
            return {"ok": False, "reason": "wrong worker"}
        threading.Thread(target=self._exit_now, args=(0,),
                         daemon=True).start()
        return {"ok": True}

    # ------------------------------------------------------------------
    # task execution
    def _h_push_task(self, body):
        spec: TaskSpec = body["spec"]
        if spec.task_type == TaskType.NORMAL:
            return self._execute_normal(spec)
        if spec.task_type == TaskType.ACTOR_CREATION:
            return self._execute_actor_creation(spec)
        return self._enqueue_actor_task(spec)

    def _h_push_task_batch(self, body):
        """Coalesced pushes: one frame carries many specs, one reply carries
        their replies in submission order. The submitter batches bursts so
        per-task interpreter + syscall costs amortize — the wire-level analog
        of the reference's C++ in-flight push pipelining
        (normal_task_submitter.cc:139,183), where per-task RPCs are cheap
        enough not to need it. Sub-replies aggregate through each task's
        DeferredReply, so nothing here blocks the handler thread."""
        specs: list[TaskSpec] = body["specs"]
        frame = _BatchFrame(self, specs)
        for i, spec in enumerate(specs):
            try:
                r = self._h_push_task({"spec": spec})
            except BaseException as e:  # noqa: BLE001
                frame.done(i, False, e)
                continue
            if isinstance(r, DeferredReply):
                r._bind(frame.finisher(i))
            else:
                frame.done(i, True, r)
        if len(specs) > 1:
            # singleton frames have no batch-mate to wait behind: the
            # aggregate reply IS the (only) task's reply, so the janitor's
            # early-flush machinery would be pure overhead
            self._watch_frame(frame)
        return frame.agg

    def _watch_frame(self, frame: "_BatchFrame"):
        """Hand a still-open batch frame to the janitor, which flushes
        completed sub-replies early once the frame outlives _EARLY_REPLY_S
        (a fast concurrent call must not wait on a slow batch-mate)."""
        with self._frames_lock:
            if frame.complete:
                return
            self._open_frames.add(frame)
            start = not self._frame_janitor_started
            self._frame_janitor_started = True
        if start:
            threading.Thread(target=self._frame_janitor_loop,
                             name="frame-janitor", daemon=True).start()
        self._frames_event.set()

    def _early_send_suspended(self, addr: tuple) -> bool:
        ts = self._early_send_failures.get(addr)
        if ts is None:
            return False
        if time.monotonic() - ts > 30.0:
            self._early_send_failures.pop(addr, None)
            return False
        return True

    def _suspend_early_sends(self, addr: tuple):
        self._early_send_failures[addr] = time.monotonic()

    def _frame_janitor_loop(self):
        while not self._shutdown.is_set():
            # clear BEFORE the snapshot: a frame registered after an empty
            # snapshot but before a clear would lose its wakeup and wait out
            # the full backstop timeout instead of ~one janitor period
            self._frames_event.clear()
            with self._frames_lock:
                frames = list(self._open_frames)
            if not frames:
                self._frames_event.wait(5.0)
                continue
            now = time.monotonic()
            for frame in frames:
                if frame.complete:
                    with self._frames_lock:
                        self._open_frames.discard(frame)
                elif now - frame.t0 > _EARLY_REPLY_S:
                    frame.flush_early()
            time.sleep(_EARLY_REPLY_S)

    def _execute_normal(self, spec: TaskSpec):
        if spec.task_id in self._cancelled_tasks:
            return self._error_reply(spec, TaskError(
                TaskCancelledError(), task_repr=spec.repr_name()))
        # Callers pipeline several pushes onto one lease (submitter
        # MAX_INFLIGHT_PER_WORKER); execution stays one-at-a-time per
        # 1-CPU lease (the reference's NormalSchedulingQueue semantics) —
        # EXCEPT that a task blocked in get()/wait() yields its slot so a
        # queued task can start (the reference's blocked-worker oversubscribe;
        # without it, two queued tasks that rendezvous through an actor
        # deadlock on one worker).
        reply = DeferredReply()

        def run():
            self._blocked_notified.sent = False
            try:
                # re-check: a cancel may have landed while this task was
                # parked in the queue behind a running task
                if spec.task_id in self._cancelled_tasks:
                    reply.send(self._error_reply(spec, TaskError(
                        TaskCancelledError(), task_repr=spec.repr_name())))
                    return
                reply.send(self._run_task(spec))
            except BaseException as e:  # noqa: BLE001
                reply.fail(e)

        self._normal_exec.submit(run)
        return reply

    def _bind_exec_thread(self):
        """Point the calling (executor) thread's API surface at this
        runtime: with in-process workers several runtimes share the
        process, and task bodies calling ray_tpu.get/put/remote must reach
        THEIR worker's runtime, not the process-global one."""
        from ray_tpu.core import api
        api._bind_thread_runtime(self)

    @staticmethod
    def _shed_if_expired(spec: TaskSpec) -> None:
        """Refuse to START work whose end-to-end deadline already passed
        (fast shed at the executor's dequeue point — core/deadline.py).
        The caller sees TaskError(DeadlineExceededError); routers/proxies
        map it to a 503 instead of retrying."""
        d = spec.deadline
        if d is not None and time.time() >= d:
            raise DeadlineExceededError(
                f"task {spec.repr_name()} deadline exceeded "
                f"{time.time() - d:.3f}s before execution started")

    def _run_task(self, spec: TaskSpec) -> dict:
        self._bind_exec_thread()
        prev_task = self._ctx.task_id
        self._ctx.task_id = spec.task_id
        self._ctx.put_counter = 0
        try:
            self._shed_if_expired(spec)
            # extract the caller's span context from the spec so nested
            # submits from the task body stitch into the same trace
            with tracing.span_from(
                    spec.trace_ctx, f"task.run:{spec.repr_name()}",
                    attrs={"task_id": spec.task_id.hex()[:16],
                           "worker_id": self.worker_id.hex()[:16],
                           "attempt": spec.attempt_number}), \
                    request_deadline.scope(spec.deadline):
                t0 = time.monotonic()
                fn = self.function_manager.get(spec.function_id)
                t1 = time.monotonic()
                args, kwargs = self._resolve_args(spec)
                t2 = time.monotonic()
                if t2 - t0 > 0.05:
                    logger.info("task %s setup: fn_get=%.3fs args=%.3fs",
                                spec.repr_name(), t1 - t0, t2 - t1)
                if spec.task_type == TaskType.ACTOR_TASK:
                    method = self._actor_method(spec.method_name)
                    result = method(*args, **kwargs)
                else:
                    result = fn(*args, **kwargs)
            return self._success_reply(spec, result)
        except BaseException as e:  # noqa: BLE001 — app errors ship to the owner
            if isinstance(e, TaskError):
                err = e
            else:
                err = TaskError(e, task_repr=spec.repr_name())
            return self._error_reply(spec, err)
        finally:
            self._ctx.task_id = prev_task

    def _resolve_args(self, spec: TaskSpec) -> tuple[tuple, dict]:
        args, kwargs = [], {}
        for a in spec.args:
            if a.is_ref:
                oid, owner, owner_addr, key = a.ref
                ref = ObjectRef(oid, owner, owner_addr, _skip_refcount=True)
                with tracing.span("task.dep_fetch", kind="object",
                                  child_only=True,
                                  attrs={"object_id": oid.hex()[:16]}):
                    value = self._get_one(
                        ref, deadline=time.monotonic() + 300.0)
            else:
                key = a.contained[0] if a.contained else None
                value = self.serialization.deserialize(
                    SerializedObject.from_buffer(a.data))
            if key is None:
                args.append(value)
            else:
                kwargs[key] = value
        return tuple(args), kwargs

    def _success_reply(self, spec: TaskSpec, result) -> dict:
        if spec.streaming:
            return self._stream_out(spec, result)
        if spec.num_returns == 0:
            return {"results": [], "error": None}
        values = [result] if spec.num_returns == 1 else list(result)
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            return self._error_reply(spec, TaskError(
                ValueError(f"task returned {len(values)} values, expected {spec.num_returns}"),
                task_repr=spec.repr_name()))
        out = []
        cfg = get_config()
        for oid, value in zip(spec.return_ids(), values):
            sobj = self.serialization.serialize(value)
            if (sobj.serialized_size() <= cfg.max_inline_object_size
                    or self.agent_addr is None):
                out.append((oid, "inline", sobj.to_bytes(), False))
            else:
                self._store_return_shm(oid, sobj, spec)
                out.append((oid, "shm", self.node_id, False))
        return {"results": out, "error": None, "attempt": spec.attempt_number}

    def _stream_out(self, spec: TaskSpec, gen) -> dict:
        """Executor side of streaming returns: report each yielded item to
        the owner as it's produced, throttled to CONSUMPTION — at most
        ``streaming_backpressure_items`` items beyond the consumer's cursor
        (ref: core_worker.proto:513 ReportGeneratorItemReturns +
        generator_backpressure_num_objects). Item-report replies carry the
        cursor; while blocked the executor polls it (the consumer advancing
        has no push path back here)."""
        cfg = get_config()
        owner = self.peer_pool.get(spec.owner_addr)
        window = max(1, cfg.streaming_backpressure_items)
        cv = threading.Condition()
        inflight = [0]
        consumed = [0]
        cancelled = [False]

        def on_ack(ok, reply):
            with cv:
                inflight[0] -= 1
                if ok and isinstance(reply, dict):
                    if reply.get("cancel"):
                        cancelled[0] = True
                    consumed[0] = max(consumed[0],
                                      reply.get("consumed", 0))
                cv.notify_all()

        def check_cancelled():
            if cancelled[0]:
                # consumer abandoned the stream: stop producing instead of
                # running the generator to completion for nobody
                raise TaskCancelledError("stream consumer abandoned")

        def throttle(next_idx: int):
            poll_failures = 0
            while True:
                check_cancelled()
                with cv:
                    if next_idx - consumed[0] < window \
                            and inflight[0] < window:
                        return
                    cv.wait(0.2)
                    if next_idx - consumed[0] < window \
                            and inflight[0] < window:
                        return
                check_cancelled()
                try:
                    r = owner.call("stream_consumed",
                                   {"task_id": spec.task_id}, timeout=5.0)
                    poll_failures = 0
                    with cv:
                        if (r or {}).get("cancel"):
                            cancelled[0] = True
                        consumed[0] = max(consumed[0],
                                          (r or {}).get("consumed", 0))
                except Exception:
                    poll_failures += 1
                    if poll_failures >= 60:  # owner unreachable ~1 min
                        raise RuntimeError(
                            "stream owner unreachable; aborting generator")

        def send(payload, next_idx: int):
            throttle(next_idx)
            with cv:
                inflight[0] += 1
            try:
                owner.call_async("stream_item", payload, callback=on_ack)
            except Exception:
                with cv:
                    inflight[0] -= 1
                    cv.notify_all()
                raise

        idx = 0
        try:
            it = iter(gen)
            while True:
                try:
                    value = next(it)
                except StopIteration:
                    break
                oid = ObjectID.for_return(spec.task_id, idx + 1)
                sobj = self.serialization.serialize(value)
                if (sobj.serialized_size() <= cfg.max_inline_object_size
                        or self.agent_addr is None):
                    item = (oid, "inline", sobj.to_bytes(), False)
                else:
                    self._store_return_shm(oid, sobj, spec)
                    item = (oid, "shm", self.node_id, False)
                send({"task_id": spec.task_id, "index": idx, "item": item,
                      "attempt": spec.attempt_number}, idx)
                idx += 1
        except TaskCancelledError:
            # abandoned stream: nothing to report, nobody listening
            return {"results": [], "error": None,
                    "attempt": spec.attempt_number}
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, TaskError) else TaskError(
                e, task_repr=spec.repr_name())
            sobj = self.serialization.serialize(err)
            if spec.retry_exceptions:
                # match non-streaming semantics: a retryable app error
                # re-runs the whole generator via the owner's retry
                # machinery instead of surfacing mid-stream
                return {"results": [], "app_error": sobj.to_bytes(),
                        "attempt": spec.attempt_number}
            oid = ObjectID.for_return(spec.task_id, idx + 1)
            send({"task_id": spec.task_id, "index": idx,
                  "item": (oid, "inline", sobj.to_bytes(), True),
                  "attempt": spec.attempt_number}, idx)
            idx += 1
        send({"task_id": spec.task_id, "index": idx, "done": True,
              "count": idx, "attempt": spec.attempt_number}, idx)
        # Barrier on all acks BEFORE replying to the task push: the
        # completion reply travels on a different connection than the item
        # reports and would otherwise race them — the owner marks the task
        # complete and then drops the late item reports as stale, hanging
        # the consumer. (call_async always fires its callback, including on
        # transport failure; the deadline is a backstop.)
        deadline = time.monotonic() + 60.0
        with cv:
            while inflight[0] > 0 and time.monotonic() < deadline:
                cv.wait(1.0)
            unacked = inflight[0]
        if unacked > 0:
            # A success reply now would race ahead of the unacked item
            # reports: the owner marks the task complete and drops them as
            # stale, hanging the consumer on the missing index. Fail the
            # reply instead so the owner's retry/failure machinery runs.
            return {"results": [],
                    "error": (f"stream {spec.task_id.hex()[:12]}: {unacked} "
                              f"item report(s) unacknowledged after 60s "
                              f"barrier; failing task instead of completing "
                              f"with items possibly dropped"),
                    "attempt": spec.attempt_number}
        return {"results": [], "error": None, "attempt": spec.attempt_number}

    def _h_task_reply_early(self, body):
        """Owner side: a push_task_batch frame gated by a slow batch-mate
        ships completed sub-replies ahead of the aggregate (see
        _h_push_task_batch). The aggregate's later copy is ignored because
        the task is no longer pending."""
        spec = self.task_manager.get_pending_spec(body["task_id"])
        if spec is not None:
            self.process_task_reply(spec, body["reply"])
        return {"ok": True}

    def _h_stream_item(self, body):
        """Owner-side item report (ref: ReportGeneratorItemReturns)."""
        return self.stream_manager.on_item(body)

    def _h_stream_consumed(self, body):
        """Executor backpressure poll: the consumer's cursor."""
        return self.stream_manager.on_consumed_query(body)

    def _store_return_shm(self, oid: ObjectID, sobj: SerializedObject, spec: TaskSpec):
        size = sobj.serialized_size()
        agent = self.peer_pool.get(self.agent_addr)
        deadline = time.monotonic() + 60.0
        while True:
            try:
                reply = agent.call_with_retry(
                    "store_create", {"object_id": oid, "size": size,
                                     "owner_addr": spec.owner_addr},
                    timeout=30.0)
                break
            except ObjectStoreFullError:
                # transient pressure (unsealed inbound transfers, nothing
                # spillable yet): wait for the store to breathe rather than
                # failing the task (reference: plasma create blocks)
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        mv = self._writable_extent(reply["shm_name"], size,
                                   reply.get("offset", 0))
        _write_serialized(mv, sobj)
        agent.call_with_retry("store_seal", {"object_id": oid}, timeout=30.0)

    def _error_reply(self, spec: TaskSpec, err: TaskError) -> dict:
        sobj = self.serialization.serialize(err)
        data = sobj.to_bytes()
        return {"results": [(oid, "inline", data, True) for oid in spec.return_ids()],
                "error": None, "attempt": spec.attempt_number}

    # ---- actors --------------------------------------------------------
    def _execute_actor_creation(self, spec: TaskSpec) -> dict:
        logger.debug("executing actor creation %s", spec.actor_id.hex()[:8])
        st = self._actor_state
        try:
            self._bind_exec_thread()
            with tracing.span_from(
                    spec.trace_ctx, f"actor.init:{spec.name}",
                    attrs={"actor_id": spec.actor_id.hex()[:16]}):
                cls = self.function_manager.get(spec.function_id)
                args, kwargs = self._resolve_args(spec)
                prev = self._ctx.task_id
                self._ctx.task_id = spec.task_id
                try:
                    instance = cls(*args, **kwargs)
                finally:
                    self._ctx.task_id = prev
            st.instance = instance
            st.actor_id = spec.actor_id
            st.pool = ThreadPoolExecutor(
                max_workers=max(1, spec.max_concurrency),
                thread_name_prefix="actor-exec")
            # named concurrency groups: independent bounded pools so e.g.
            # "io" calls can't starve "compute" (ref:
            # task_execution/concurrency_group_manager.h)
            for gname, gmax in (spec.concurrency_groups or {}).items():
                st.group_pools[gname] = ThreadPoolExecutor(
                    max_workers=max(1, int(gmax)),
                    thread_name_prefix=f"actor-{gname}")
                st.group_limits[gname] = max(1, int(gmax))
            if spec.is_async_actor:
                import asyncio
                st.loop = asyncio.new_event_loop()
                threading.Thread(target=st.loop.run_forever,
                                 name="actor-loop", daemon=True).start()
            return {"error": None, "addr": self.addr}
        except BaseException as e:  # noqa: BLE001
            logger.exception("actor creation failed")
            return {"error": f"{type(e).__name__}: {e}"}

    def _enqueue_actor_task(self, spec: TaskSpec):
        """In-order dispatch per caller (ref: actor_scheduling_queue.cc).

        Reply-later: returns a DeferredReply immediately so the RPC thread is
        never pinned for the duration of the call — per-worker concurrency is
        bounded only by the actor's max_concurrency pool (sync methods) or
        the event loop (async methods), matching the reference's fiber-based
        executor semantics (task_execution/fiber.h)."""
        st = self._actor_state
        if st.instance is None:
            return {"results": [], "error": "actor not initialized"}
        if st.exiting:
            # killed (or exit_actor'd) but the process hasn't exited yet: a
            # racing call must observe death, not execute
            from ray_tpu.exceptions import ActorDiedError
            return self._error_reply(spec, TaskError(
                ActorDiedError("actor is exiting"),
                task_repr=spec.repr_name()))
        caller = spec.caller_id.binary()
        reply = DeferredReply()
        with st.lock:
            expected = st.expected_seq.get(caller, 0)
            if spec.seq_no == -1 or spec.allow_out_of_order:
                self._dispatch_actor_task(spec, reply)
            elif spec.seq_no == expected:
                st.expected_seq[caller] = expected + 1
                self._dispatch_actor_task(spec, reply)
                pend = st.pending.get(caller, {})
                nxt = st.expected_seq[caller]
                while nxt in pend:
                    pspec, preply = pend.pop(nxt)
                    self._dispatch_actor_task(pspec, preply)
                    nxt += 1
                    st.expected_seq[caller] = nxt
            elif spec.seq_no < expected:
                # duplicate resubmission after reconnect: re-execute is unsafe;
                # reply with error so the owner retries via status
                self._dispatch_actor_task(spec, reply)
                st.expected_seq[caller] = spec.seq_no + 1
            else:
                st.pending.setdefault(caller, {})[spec.seq_no] = (spec, reply)
        return reply

    def _actor_method(self, name: str):
        """Resolve an actor method by name. ``__rtpu_call__`` is the generic
        entry (reference: actor.__ray_call__): the first argument is a
        callable invoked as fn(instance, *args, **kwargs) — what lets
        framework code (e.g. the compiled-pipeline stage loop) run on ANY
        user actor without the class pre-declaring a method."""
        inst = self._actor_state.instance
        if name == "__rtpu_call__":
            return lambda fn, *a, **k: fn(inst, *a, **k)
        return getattr(inst, name)

    def _actor_group_for(self, spec: TaskSpec) -> str:
        st = self._actor_state
        group = spec.concurrency_group
        if not group:
            method = getattr(st.instance, spec.method_name, None)
            group = getattr(method, "_concurrency_group", "")
        if group and group not in st.group_pools:
            # a typo'd group silently landing in the default (often
            # 1-wide) pool would reproduce the starvation groups prevent
            raise ValueError(
                f"unknown concurrency group {group!r}; declared: "
                f"{sorted(st.group_pools) or 'none'}")
        return group

    def _actor_pool_for(self, group: str):
        st = self._actor_state
        if group:
            return st.group_pools[group]
        return st.pool

    def _dispatch_actor_task(self, spec: TaskSpec, reply: DeferredReply):
        st = self._actor_state
        try:
            group = self._actor_group_for(spec)
        except ValueError as e:
            reply.send(self._error_reply(spec, TaskError(
                e, task_repr=spec.repr_name())))
            return
        pool = self._actor_pool_for(group)
        method = (None if spec.method_name == "__rtpu_call__"
                  else getattr(st.instance, spec.method_name, None))
        import inspect
        if (st.loop is not None and method is not None
                and inspect.iscoroutinefunction(method)):
            # async method: resolve args on a pool thread (may ray.get), then
            # run the coroutine on the actor's event loop — no thread held
            # while the method awaits, so thousands of calls can be in flight
            import asyncio

            def schedule():
                try:
                    args, kwargs = self._resolve_args(spec)
                except BaseException as e:  # noqa: BLE001
                    reply.fail(e)
                    return

                async def arun():
                    try:
                        sem = None
                        if group:
                            # the pool only bounds the scheduling thunk;
                            # the GROUP bound for coroutines is a loop-side
                            # semaphore (ref: fiber.h per-group fibers)
                            sem = st.group_sems.get(group)
                            if sem is None:
                                sem = st.group_sems[group] =                                     asyncio.Semaphore(st.group_limits[group])
                        if sem is not None:
                            async with sem:
                                reply.send(await self._run_actor_task_async(
                                    spec, method, args, kwargs))
                        else:
                            reply.send(await self._run_actor_task_async(
                                spec, method, args, kwargs))
                    except BaseException as e:  # noqa: BLE001
                        reply.fail(e)

                asyncio.run_coroutine_threadsafe(arun(), st.loop)

            pool.submit(schedule)
            return

        def run():
            try:
                reply.send(self._run_actor_task(spec))
            except BaseException as e:  # noqa: BLE001
                reply.fail(e)

        pool.submit(run)

    async def _run_actor_task_async(self, spec: TaskSpec, method,
                                    args, kwargs) -> dict:
        self._bind_exec_thread()
        st = self._actor_state
        prev = self._ctx.task_id
        self._ctx.task_id = spec.task_id
        self._ctx.put_counter = 0
        try:
            self._shed_if_expired(spec)
            with tracing.span_from(
                    spec.trace_ctx, f"actor.run:{spec.name or spec.method_name}",
                    attrs={"task_id": spec.task_id.hex()[:16],
                           "worker_id": self.worker_id.hex()[:16]}), \
                    request_deadline.scope(spec.deadline):
                result = await method(*args, **kwargs)
            reply = self._success_reply(spec, result)
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, SystemExit):
                reply = self._exit_actor_reply(spec)
            else:
                reply = self._error_reply(
                    spec, e if isinstance(e, TaskError)
                    else TaskError(e, task_repr=spec.repr_name()))
        finally:
            self._ctx.task_id = prev
        if st.exiting:
            self._do_exit_actor()
        return reply

    def _run_actor_task(self, spec: TaskSpec) -> dict:
        self._bind_exec_thread()
        st = self._actor_state
        prev = self._ctx.task_id
        self._ctx.task_id = spec.task_id
        self._ctx.put_counter = 0
        try:
            self._shed_if_expired(spec)
            with tracing.span_from(
                    spec.trace_ctx, f"actor.run:{spec.name or spec.method_name}",
                    attrs={"task_id": spec.task_id.hex()[:16],
                           "worker_id": self.worker_id.hex()[:16]}), \
                    request_deadline.scope(spec.deadline):
                method = self._actor_method(spec.method_name)
                args, kwargs = self._resolve_args(spec)
                import inspect
                if inspect.iscoroutinefunction(method) and st.loop is not None:
                    import asyncio
                    result = asyncio.run_coroutine_threadsafe(
                        method(*args, **kwargs), st.loop).result()
                else:
                    result = method(*args, **kwargs)
            reply = self._success_reply(spec, result)
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, SystemExit):
                reply = self._exit_actor_reply(spec)
            else:
                reply = self._error_reply(
                    spec, e if isinstance(e, TaskError) else TaskError(e, task_repr=spec.repr_name()))
        finally:
            self._ctx.task_id = prev
        if st.exiting:
            self._do_exit_actor()
        return reply

    def _exit_actor_reply(self, spec: TaskSpec) -> dict:
        self._actor_state.exiting = True
        return self._success_reply(spec, None)

    def request_exit_actor(self):
        self._actor_state.exiting = True

    def _do_exit_actor(self):
        def exit_later():
            # let the final reply flush to the caller before announcing death
            time.sleep(0.25)
            try:  # last metrics before the CP retracts this worker's series
                from ray_tpu.util import metrics as _metrics
                _metrics.flush_now()
            except Exception:
                pass
            try:
                self.cp_client.call(
                    "actor_exited", {"actor_id": self._actor_state.actor_id}, timeout=5.0)
            except Exception:
                pass
            time.sleep(0.1)
            self.on_exit(0)

        threading.Thread(target=exit_later, daemon=True).start()

    # ------------------------------------------------------------------
    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def work():
            try:
                fut.set_result(self.get([ref])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=work, daemon=True).start()
        return fut

    def shutdown(self):
        self._shutdown.set()
        if self.mode == "driver":
            try:  # the CP must not keep publishing logs to a dead driver
                # (strike-GC drops the sub anyway once pushes start failing)
                # graftlint: fire-and-forget
                self.cp_client.notify(
                    "unsubscribe",
                    {"channel": f"worker_logs:{self.job_id.hex()}",
                     "addr": self.addr})
            except Exception:
                pass
        self.flush_task_events()
        tracing.flush()
        # final metrics flush while cp_client is still open; a joiner (head
        # process: the CP owns the shared flusher) flushes without stopping
        from ray_tpu.util import metrics as _metrics
        if self._metrics_flusher is not None:
            _metrics.stop_flusher(self._metrics_flusher)
        else:
            _metrics.flush_now()
        self.normal_submitter.shutdown()
        self.actor_submitter.shutdown()
        self._server.stop()
        self.peer_pool.close_all()
        self.cp_client.close()
        self.shm_client.close()


def _is_device_array(value) -> bool:
    """True for a jax.Array (any backend) WITHOUT importing jax — a value
    can't be one unless jax is already loaded in this process."""
    import sys
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


def _write_serialized(mv: memoryview, sobj: SerializedObject):
    class _MvWriter:
        def __init__(self, mv):
            self.mv = mv
            self.off = 0

        def write(self, b):
            n = len(b)
            self.mv[self.off:self.off + n] = b
            self.off += n

    sobj.write_into(_MvWriter(mv))
