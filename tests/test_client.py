"""Ray Client (remote driver) tests — reference model:
python/ray/tests/test_client.py basic API coverage over a ray:// session."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.client import ClientServer


@pytest.fixture
def client_cluster():
    """A cluster + client server, with the test process connecting as a
    remote driver (its local runtime is the ClientRuntime)."""
    ray_tpu.shutdown()
    # head runtime in-process (owns CP + agent)
    ctx = ray_tpu.init(num_cpus=4)
    from ray_tpu.core import api
    head_rt = api._runtime
    srv = ClientServer(head_rt.cp_addr, host="127.0.0.1")
    # detach the head runtime so init() can run again in client mode,
    # but keep the head processes alive
    head = api._head
    api._runtime, api._head = None, None
    ray_tpu.init(address=f"ray_tpu://127.0.0.1:{srv.addr[1]}")
    yield
    ray_tpu.shutdown()
    srv.stop()
    api._runtime, api._head = head_rt, head
    ray_tpu.shutdown()


def test_client_put_get_task(client_cluster):
    ref = ray_tpu.put({"a": np.arange(8)})
    out = ray_tpu.get(ref, timeout=30.0)
    assert list(out["a"]) == list(range(8))

    @ray_tpu.remote
    def add(x, y):
        return x + y

    assert ray_tpu.get(add.remote(2, 3), timeout=60.0) == 5
    # ObjectRef args resolve server-side
    assert ray_tpu.get(add.remote(ray_tpu.put(10), 5), timeout=60.0) == 15


def test_client_wait_and_errors(client_cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(4)]
    ready, pending = ray_tpu.wait(refs, num_returns=4, timeout=60.0)
    assert len(ready) == 4 and not pending
    assert sorted(ray_tpu.get(ready, timeout=30.0)) == [0, 1, 4, 9]

    @ray_tpu.remote
    def boom():
        raise ValueError("client-boom")

    with pytest.raises(Exception, match="client-boom"):
        ray_tpu.get(boom.remote(), timeout=60.0)


def test_client_actors(client_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(100)
    assert ray_tpu.get(c.inc.remote(), timeout=60.0) == 101
    assert ray_tpu.get(c.inc.remote(5), timeout=60.0) == 106
    # cluster state APIs proxy through (cp passthrough)
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 4
