"""Benchmark: Llama pretraining step throughput (tokens/sec/chip).

North-star metric per BASELINE.json ("Ray Train tokens/sec/chip @
Llama-3-8B"); the reference repo publishes no number for it ("published": {}),
so vs_baseline reports model-FLOPs utilization (MFU) against the chip's bf16
roofline instead (1.0 = peak matmul throughput).

Runs an A/B over attention implementations (dense einsum vs the Pallas flash
kernel, ops/attention.py) on the largest Llama config that fits the visible
chip, and reports the better one as the headline with both in "extra".
The true 8B config needs a v5p-64 pod (BASELINE target); one v5e chip tops
out around ~2B params with remat+bf16, so the bench scales the config to the
chip and says so rather than faking the 8B label.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


# bf16 peak TFLOP/s per chip for MFU reporting (best-effort device match)
_PEAK_TFLOPS = {
    "v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0,
}


def _peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    return _PEAK_TFLOPS["v5e"]  # conservative default


def _run_config(cfg, batch: int, seq: int, steps: int, warmup: int, dev,
                optimizer: str = "adafactor"):
    from ray_tpu.models import llama
    from ray_tpu.train import spmd

    mesh = spmd.make_mesh(1, devices=[dev])
    # adafactor: adam's fp32 moments cost 8 bytes/param — most of one v5e's
    # HBM at 1.5B params; factored state frees it for the "dots" remat
    # policy (saved matmul outputs, no backward recompute), the single
    # biggest measured MFU lever on this chip
    opt = spmd.default_optimizer(warmup_steps=10, decay_steps=1000,
                                 name=optimizer)
    state, sh = spmd.sharded_create_state(
        lambda: llama.init_params(jax.random.PRNGKey(0), cfg), opt, mesh,
        params_logical_axes=llama.logical_axes(cfg))
    step = spmd.make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg, mesh), opt, mesh, sh)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)
    batch_data = spmd.shard_batch({"tokens": tokens}, mesh)

    # NOTE: force a device->host transfer as the sync barrier —
    # block_until_ready is not a reliable fence over the axon tunnel.
    for _ in range(warmup):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt


def main() -> None:
    import dataclasses

    from ray_tpu.models import llama

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        # Measured recipe for one v5e chip at 1.5B params / seq 2048 (the 8B
        # config's sequence length; the 8B model itself needs a pod —
        # BASELINE's v5p-64): flash attention + "dots" remat (no backward
        # recompute) + adafactor + batch 4. Sweep results on this chip:
        # full-remat b8 flash 0.446 MFU, dots b4 flash 0.49-0.51, dense
        # dots b4 0.42, 3.6B full-remat b4 0.39.
        base = llama.llama3_1b(max_seq_len=2048, remat_policy="dots",
                               ce_chunk=2048)
        batch, seq, steps, warmup = 4, 2048, 10, 3
        impls = ("dense", "flash")
        optimizer = "adafactor"  # frees adam's 12GB of fp32 moments for dots
    else:
        base = llama.llama_tiny()
        batch, seq, steps, warmup = 8, 64, 5, 2
        impls = ("dense",)  # pallas interpret mode is too slow to bench
        optimizer = "adamw"  # the BASELINE recipe; tiny model fits anywhere

    results: dict[str, float] = {}
    for impl in impls:
        cfg = dataclasses.replace(base, attn_impl=impl)
        try:
            results[impl] = _run_config(cfg, batch, seq, steps, warmup, dev,
                                        optimizer=optimizer)
        except Exception as e:  # noqa: BLE001 - report the surviving impl
            results[impl] = float("nan")
            print(f"# {impl} failed: {e!r}", file=sys.stderr)

    ok = {k: v for k, v in results.items() if v == v}  # drop NaN (failed)
    best_impl = max(ok, key=ok.get) if ok else "none"
    tok_per_s = ok.get(best_impl, float("nan"))

    n_params = llama.num_params(base)
    peak = _peak_tflops(dev)

    def mfu(tps: float) -> float | None:
        if not on_tpu or tps != tps:
            return None
        return round((6.0 * n_params * tps) / (peak * 1e12), 4)

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1) if tok_per_s == tok_per_s else None,
        "unit": "tokens/s/chip",
        "vs_baseline": mfu(tok_per_s),
        "extra": {
            "attn_impl": best_impl,
            "per_impl_tokens_per_s": {k: (round(v, 1) if v == v else None)
                                      for k, v in results.items()},
            "per_impl_mfu": {k: mfu(v) for k, v in results.items()},
            "params": n_params,
            "batch": batch, "seq": seq,
            "device": getattr(dev, "device_kind", str(dev)),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
