"""ray_tpu.serve — model serving (reference: python/ray/serve/).

Controller reconciliation + pow-2 router + replicas + dynamic batching +
HTTP ingress; the LLM path (continuous batching on TPU) lives in
ray_tpu.serve.llm.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    detailed_status,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start_http_proxies,
    start_http_proxy,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.grpc_ingress import start_grpc_proxy
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)

__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentConfig",
    "DeploymentHandle", "DeploymentResponse", "DeploymentResponseGenerator",
    "batch", "delete", "deployment", "detailed_status", "get_app_handle",
    "get_deployment_handle", "get_multiplexed_model_id", "multiplexed",
    "run", "shutdown", "start_grpc_proxy", "start_http_proxies",
    "start_http_proxy", "status",
]
