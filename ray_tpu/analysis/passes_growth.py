"""graftlint unbounded-growth pass.

The repo invariant since PR 4 (metrics GC): any container an RPC/event
handler grows must have a visible retraction path — a cap/trim, a TTL
sweep, or a death-event GC. This pass finds class-attribute dicts/lists/
sets initialized empty in ``__init__`` and mutated from handler-reachable
methods (``_h_*`` / ``_on_*`` / ``on_*`` / ``handle*``, plus methods a
handler calls directly) in classes that never shrink them.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ray_tpu.analysis.core import ModuleSource, Pass, register
from ray_tpu.analysis.lockmodel import self_calls

HANDLER_RE = re.compile(r"^(_h_|_on_|on_|handle)")

_EMPTY_CALLS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                "Counter"}
_GROW_ATTRS = {"append", "add", "extend", "insert", "setdefault", "update",
               "appendleft"}
_SHRINK_ATTRS = {"pop", "popitem", "popleft", "clear", "remove", "discard"}


def _container_attrs(cls: ast.ClassDef) -> dict[str, int]:
    """self.X = {} / [] / set() / dict() / OrderedDict() / defaultdict(..)
    assignments in __init__ -> {attr: lineno}. deque(maxlen=...) and any
    non-empty initializer are considered bounded/deliberate."""
    init = next((m for m in cls.body
                 if isinstance(m, ast.FunctionDef) and m.name == "__init__"),
                None)
    if init is None:
        return {}
    out: dict[str, int] = {}
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            v = node.value
            empty = False
            if isinstance(v, (ast.Dict, ast.List, ast.Set)) \
                    and not getattr(v, "keys", getattr(v, "elts", None)):
                empty = True
            elif isinstance(v, ast.Call):
                name = v.func.id if isinstance(v.func, ast.Name) else (
                    v.func.attr if isinstance(v.func, ast.Attribute) else "")
                if name in _EMPTY_CALLS and not v.args:
                    empty = True
                elif name == "deque" and not any(
                        kw.arg == "maxlen" for kw in v.keywords):
                    empty = True
            if empty:
                out[t.attr] = node.lineno
    return out


def _attr_of(node: ast.AST) -> Optional[str]:
    """self.X for self.X / self.X[...] expressions."""
    if isinstance(node, ast.Subscript):
        return _attr_of(node.value)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@register
class UnboundedGrowthPass(Pass):
    id = "unbounded-growth"
    title = "handler-fed container with no visible bound"
    hint = ("add a cap/trim (del x[:-N], len() check), a TTL sweep, or a "
            "death-event retraction — or pragma "
            "`# graftlint: disable=unbounded-growth` with the bound's "
            "location")

    def run(self, module: ModuleSource) -> list:
        findings = []
        for cls in ast.walk(module.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(module, cls))
        return [f for f in findings if f is not None]

    def _check_class(self, module: ModuleSource, cls: ast.ClassDef) -> list:
        containers = _container_attrs(cls)
        if not containers:
            return []
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        handler_names = {n for n in methods if HANDLER_RE.match(n)}
        # one hop: methods a handler calls directly are handler-reachable
        reachable = set(handler_names)
        for h in handler_names:
            reachable |= self_calls(methods[h]) & set(methods)

        shrunk: set[str] = set()
        for m in methods.values():
            for node in ast.walk(m):
                # del self.X[...] / del self.X
                if isinstance(node, ast.Delete):
                    for t in node.targets:
                        a = _attr_of(t)
                        if a:
                            shrunk.add(a)
                # self.X.pop(...) etc.
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SHRINK_ATTRS:
                    a = _attr_of(node.func.value)
                    if a:
                        shrunk.add(a)
                # reassignment outside __init__ resets the container
                elif isinstance(node, ast.Assign) and m.name != "__init__":
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            a = _attr_of(t)
                            if a:
                                shrunk.add(a)
                # an explicit len() comparison counts as a visible cap
                elif isinstance(node, ast.Compare):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Call) \
                                and isinstance(sub.func, ast.Name) \
                                and sub.func.id == "len" and sub.args:
                            a = _attr_of(sub.args[0])
                            if a:
                                shrunk.add(a)

        findings = []
        seen: set[tuple] = set()  # one finding per (method, attr)
        for name in sorted(reachable):
            m = methods[name]
            for node in ast.walk(m):
                grown = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            grown = _attr_of(t)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _GROW_ATTRS:
                    grown = _attr_of(node.func.value)
                if not grown or grown not in containers or grown in shrunk \
                        or (name, grown) in seen:
                    continue
                seen.add((name, grown))
                findings.append(self.emit(
                    module, node, f"{cls.name}.{name}",
                    f"self.{grown} grows in handler path {name} but "
                    f"{cls.name} never caps, trims, or retracts it",
                    f"self.{grown}",
                    extra_pragma_lines=(m.lineno, containers[grown])))
        return findings
