"""Scheduling policies: resource fitting, hybrid node scoring, PG bundle packing.

TPU-native analog of the reference's scheduling policies
(/root/reference/src/ray/raylet/scheduling/policy/): the hybrid policy
(hybrid_scheduling_policy.cc) prefers the local node until utilization crosses a
threshold, then packs by score; spread/affinity/label policies mirror
scheduling_strategies.py. PG bundle placement mirrors
bundle_scheduling_policy.cc (PACK/SPREAD/STRICT_PACK/STRICT_SPREAD).

TPU-first addition (SURVEY.md §7 phase 4): node labels carry slice topology
({"slice_name", "tpu_worker_id", "pod_type", "topology"}) and scoring penalizes
ICI distance — same slice beats same pod beats cross-DCN — so gang placement
rides the ICI mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import NodeID
from ray_tpu.core.task_spec import (
    DefaultStrategy,
    NodeAffinityStrategy,
    NodeLabelStrategy,
    SchedulingStrategy,
    SpreadStrategy,
)

# ---- resource sets ------------------------------------------------------


def fits(avail: dict[str, float], req: dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in req.items() if v > 0)


def subtract(avail: dict[str, float], req: dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def add(avail: dict[str, float], req: dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v


@dataclass
class NodeView:
    """Scheduler's view of one node (ref: ClusterResourceManager node view)."""
    node_id: NodeID
    addr: tuple[str, int]
    total: dict[str, float]
    available: dict[str, float]
    labels: dict[str, str] = field(default_factory=dict)
    alive: bool = True

    def utilization(self) -> float:
        utils = []
        for k, tot in self.total.items():
            if tot > 0:
                utils.append(1.0 - self.available.get(k, 0.0) / tot)
        return max(utils) if utils else 0.0


def _ici_distance(a_labels: dict[str, str], b_labels: dict[str, str]) -> float:
    """0 = same slice (pure ICI), 0.5 = same pod type (fast DCN), 1 = far."""
    if not a_labels or not b_labels:
        return 1.0
    if a_labels.get("slice_name") and a_labels.get("slice_name") == b_labels.get("slice_name"):
        return 0.0
    if a_labels.get("pod_type") and a_labels.get("pod_type") == b_labels.get("pod_type"):
        return 0.5
    return 1.0


def _match_labels(labels: dict[str, str], constraints: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in constraints.items())


def pick_node(
    nodes: Iterable[NodeView],
    resources: dict[str, float],
    strategy: SchedulingStrategy | None = None,
    local_node_id: NodeID | None = None,
    affinity_labels: dict[str, str] | None = None,
) -> NodeView | None:
    """Pick the best feasible node, or None if infeasible right now."""
    cfg = get_config()
    strategy = strategy or DefaultStrategy()
    feasible = [n for n in nodes if n.alive and fits(n.available, resources)]

    if isinstance(strategy, NodeAffinityStrategy):
        for n in feasible:
            if n.node_id.hex() == strategy.node_id_hex:
                return n
        if strategy.soft:
            feasible2 = feasible
        else:
            return None
        feasible = feasible2

    if isinstance(strategy, NodeLabelStrategy):
        hard = [n for n in feasible if _match_labels(n.labels, strategy.hard)]
        if not hard:
            return None
        soft = [n for n in hard if _match_labels(n.labels, strategy.soft)]
        feasible = soft or hard

    if not feasible:
        return None

    if isinstance(strategy, SpreadStrategy):
        # spread_scheduling_policy.cc: least-utilized first
        return min(feasible, key=lambda n: (n.utilization(), n.node_id.hex()))

    # hybrid: local first while under threshold, then best-scored
    if local_node_id is not None:
        for n in feasible:
            if n.node_id == local_node_id and n.utilization() < cfg.hybrid_threshold:
                return n

    def score(n: NodeView) -> tuple:
        ici = _ici_distance(affinity_labels or {}, n.labels) if affinity_labels else 0.0
        return (n.utilization() + cfg.ici_distance_weight * ici, n.node_id.hex())

    return min(feasible, key=score)


# ---- placement group bundle placement ----------------------------------


def place_bundles(
    nodes: list[NodeView],
    bundles: list[dict[str, float]],
    strategy: str,
) -> list[NodeID] | None:
    """Return one NodeID per bundle, or None if infeasible
    (ref: bundle_scheduling_policy.cc). For TPU gang bundles the STRICT_SPREAD
    + slice-label path places one bundle per slice host atomically
    (generalizing the head-resource trick of _private/accelerators/tpu.py:145)."""
    avail = {n.node_id: dict(n.available) for n in nodes if n.alive}
    order = sorted((n for n in nodes if n.alive),
                   key=lambda n: (n.utilization(), n.node_id.hex()))

    def try_strict_pack() -> list[NodeID] | None:
        for n in order:
            a = dict(avail[n.node_id])
            if all(_take(a, b) for b in bundles):
                return [n.node_id] * len(bundles)
        return None

    def _take(a: dict[str, float], req: dict[str, float]) -> bool:
        if not fits(a, req):
            return False
        subtract(a, req)
        return True

    if strategy == "STRICT_PACK":
        return try_strict_pack()

    if strategy == "STRICT_SPREAD":
        placed: list[NodeID] = []
        used: set[NodeID] = set()
        for b in bundles:
            found = None
            for n in order:
                if n.node_id in used:
                    continue
                if fits(avail[n.node_id], b):
                    found = n.node_id
                    break
            if found is None:
                return None
            subtract(avail[found], b)
            used.add(found)
            placed.append(found)
        return placed

    if strategy == "SPREAD":
        placed = []
        rr = list(order)
        for i, b in enumerate(bundles):
            found = None
            # best-effort distinct nodes, round-robin over least utilized
            for n in rr[i % len(rr):] + rr[: i % len(rr)]:
                if fits(avail[n.node_id], b):
                    found = n.node_id
                    break
            if found is None:
                return None
            subtract(avail[found], b)
            placed.append(found)
        return placed

    # PACK (default): prefer one node, fall back to fewest nodes greedily
    res = try_strict_pack()
    if res is not None:
        return res
    placed = []
    for b in bundles:
        found = None
        # prefer nodes already used
        for nid in placed:
            if fits(avail[nid], b):
                found = nid
                break
        if found is None:
            for n in order:
                if fits(avail[n.node_id], b):
                    found = n.node_id
                    break
        if found is None:
            return None
        subtract(avail[found], b)
        placed.append(found)
    return placed


def place_slice_bundles(
    nodes: list[NodeView], bundles: list[dict[str, float]]
) -> list[NodeID] | None:
    """Atomic whole-slice placement: all bundles must land on hosts of ONE TPU
    slice, one bundle per slice worker ordered by tpu_worker_id (SURVEY.md §7
    phase 4 'slice bundle'; replaces the reference's TPU-{pod}-head resource
    trick, tpu.py:145)."""
    slices: dict[str, list[NodeView]] = {}
    for n in nodes:
        if n.alive and n.labels.get("slice_name"):
            slices.setdefault(n.labels["slice_name"], []).append(n)
    for _, members in sorted(slices.items()):
        members.sort(key=lambda n: int(n.labels.get("tpu_worker_id", "0")))
        if len(members) < len(bundles):
            continue
        avail = {n.node_id: dict(n.available) for n in members}
        placed = []
        ok = True
        for b, n in zip(bundles, members):
            if not fits(avail[n.node_id], b):
                ok = False
                break
            subtract(avail[n.node_id], b)
            placed.append(n.node_id)
        if ok:
            return placed
    return None
