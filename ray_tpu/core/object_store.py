"""Node-local shared-memory object store (plasma equivalent).

TPU-native analog of the reference's plasma store
(/root/reference/src/ray/object_manager/plasma/store.cc, plasma_allocator.cc,
eviction_policy.cc): objects live in OS shared memory, readers map them
zero-copy, the per-node agent owns lifecycle (create/seal/pin/evict/delete) with
LRU eviction of unpinned sealed objects when capacity is exceeded.

Two backends share the ShmStore interface:
- this pure-python backend: one ``multiprocessing.shared_memory`` segment per
  object (simple, portable);
- the native C++ arena store in ``ray_tpu/_native`` (single mapped arena +
  free-list allocator), used when built (config.use_native_object_store).

TPU twist (SURVEY.md §7 phase 2): sealed objects carry a ``device_hint`` so a
get on a TPU host can ``device_put`` straight from shm into HBM without an
extra host copy.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory

from ray_tpu.core.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

# Arenas living in THIS process (agent-side native stores), keyed by shm
# name: same-process clients write through the agent's warm mapping (pages
# materialized by the C++ pre-toucher) instead of faulting in their own.
_LOCAL_ARENAS: dict[str, "NativeObjectStore"] = {}
_ARENA_LOCK = threading.Lock()


def local_arena(shm_name: str) -> "NativeObjectStore | None":
    """The in-process native store owning ``shm_name``, if any."""
    with _ARENA_LOCK:
        return _LOCAL_ARENAS.get(shm_name)


@dataclass
class _ObjMeta:
    shm_name: str
    size: int
    sealed: bool = False
    pinned: bool = True  # pinned on create until the owner unpins (ref: PinObjectIDs)
    device_hint: str = ""
    created_at: float = field(default_factory=time.monotonic)


class ShmStore:
    """Agent-side registry + allocator. All mutations go through the node agent's
    RPC handlers; clients attach to segments by name for zero-copy reads."""

    def __init__(self, capacity_bytes: int, prefix: str = "rtpu"):
        self.capacity = capacity_bytes
        self.prefix = prefix
        self._lock = threading.Lock()
        self._objects: OrderedDict[ObjectID, _ObjMeta] = OrderedDict()  # LRU order
        self._segments: dict[str, shared_memory.SharedMemory] = {}
        self._used = 0
        self.num_evicted = 0
        self.on_evict = None  # callback(ObjectID) — notify owner of lost copy

    # ---- lifecycle ----------------------------------------------------
    def create(self, object_id: ObjectID, size: int,
               device_hint: str = "") -> tuple[str, int]:
        """Returns (shm_name, offset). Offset is always 0 for this backend
        (one segment per object); the native arena backend returns real
        offsets into its single segment."""
        with self._lock:
            if object_id in self._objects:
                meta = self._objects[object_id]
                return meta.shm_name, 0
            self._evict_until(size)
            if self._used + size > self.capacity:
                raise ObjectStoreFullError(
                    f"object of {size} bytes does not fit: {self._used}/{self.capacity} used")
            name = f"{self.prefix}_{object_id.hex()[:24]}"
            seg = shared_memory.SharedMemory(name=name, create=True, size=max(size, 1))
            self._segments[name] = seg
            self._objects[object_id] = _ObjMeta(shm_name=name, size=size, device_hint=device_hint)
            self._used += size
            return name, 0

    def seal(self, object_id: ObjectID):
        with self._lock:
            meta = self._objects.get(object_id)
            if meta is None:
                raise KeyError(f"seal of unknown object {object_id}")
            meta.sealed = True
            self._objects.move_to_end(object_id)

    def get_meta(self, object_id: ObjectID) -> tuple | None:
        """(shm_name, offset, size, device_hint, copy_on_read) of a sealed
        object. copy_on_read=False: per-object segments stay valid while
        mapped even after unlink, so zero-copy reads are safe."""
        with self._lock:
            meta = self._objects.get(object_id)
            if meta is None or not meta.sealed:
                return None
            self._objects.move_to_end(object_id)  # LRU touch
            return (meta.shm_name, 0, meta.size, meta.device_hint, False)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            m = self._objects.get(object_id)
            return m is not None and m.sealed

    def pin(self, object_id: ObjectID, pinned: bool = True):
        """Owner pins primary copies while refs are live
        (ref: node_manager.proto:479 PinObjectIDs)."""
        with self._lock:
            meta = self._objects.get(object_id)
            if meta is not None:
                meta.pinned = pinned

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._delete_locked(object_id)

    def _delete_locked(self, object_id: ObjectID):
        meta = self._objects.pop(object_id, None)
        if meta is None:
            return
        seg = self._segments.pop(meta.shm_name, None)
        self._used -= meta.size
        if seg is not None:
            try:
                seg.close()
                seg.unlink()
            except Exception:
                pass

    def _evict_until(self, need: int):
        """Evict unpinned sealed objects in LRU order (ref: eviction_policy.cc)."""
        if self._used + need <= self.capacity:
            return
        victims = [oid for oid, m in self._objects.items() if m.sealed and not m.pinned]
        for oid in victims:
            if self._used + need <= self.capacity:
                break
            self._delete_locked(oid)
            self.num_evicted += 1
            if self.on_evict is not None:
                try:
                    self.on_evict(oid)
                except Exception:
                    pass

    def read_bytes(self, object_id: ObjectID, offset: int = 0,
                   size: int | None = None) -> tuple[int, bytes] | None:
        """Range copy-out for chunked cross-node transfer
        (ref: object_manager ObjectBufferPool chunking). Returns
        (total_size, chunk)."""
        meta = self.get_meta(object_id)
        if meta is None:
            return None
        seg = self._segments.get(meta[0])
        if seg is None:
            return None
        total = meta[2]
        end = total if size is None else min(total, offset + size)
        return total, bytes(seg.buf[offset:end])

    def write_bytes(self, object_id: ObjectID, data: bytes):
        """Write a received remote copy (ref: object_manager.cc chunked push)."""
        name, _off = self.create(object_id, len(data))
        seg = self._segments[name]
        seg.buf[: len(data)] = data
        self.seal(object_id)

    def write_chunk(self, object_id: ObjectID, offset: int, data: bytes,
                    total: int):
        """Streamed chunk write: create on first chunk, seal when the last
        byte lands (ref: ObjectBufferPool chunked writes). The caller is the
        single writer for the object."""
        name, _off = self.create(object_id, total)
        seg = self._segments[name]
        seg.buf[offset:offset + len(data)] = data
        if offset + len(data) >= total:
            self.seal(object_id)

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_evicted": self.num_evicted,
            }

    def shutdown(self):
        with self._lock:
            for oid in list(self._objects):
                self._delete_locked(oid)


class _MappedSegment:
    """Direct /dev/shm mmap attach. Unlike multiprocessing.SharedMemory this
    never touches the resource tracker (we don't own the segment — the node
    agent does) and tolerates still-exported buffer views at close (readers
    may hold zero-copy numpy arrays into the mapping; the OS reclaims at
    process exit — same lifetime model as plasma's client-side mappings,
    plasma/client.cc)."""

    def __init__(self, name: str):
        import mmap
        self.path = "/dev/shm/" + name.lstrip("/")
        self._f = open(self.path, "r+b")
        self.mm = mmap.mmap(self._f.fileno(), 0)
        self._f.close()
        # Populate this process's page table in the background: the agent's
        # pre-toucher materialized the pages, but OUR mapping still pays a
        # minor fault per 4 KiB on first touch (~1.6 GB/s inside a cold
        # copy vs ~3.2 with populated read PTEs). Reads only — this client
        # does not own the data.
        if len(self.mm) >= (64 << 20):
            threading.Thread(target=self._prefault, name="shm-prefault",
                             daemon=True).start()

    def _prefault(self):
        try:
            mv = memoryview(self.mm)
            # one C-level strided copy touches every page (bytes() of a
            # step-4096 view); chunked so the transient buffer stays small
            # and a racing close fails at a chunk boundary
            chunk = 256 << 20
            for start in range(0, len(mv), chunk):
                bytes(mv[start:start + chunk:4096])
        except (ValueError, IndexError, BufferError):
            pass  # mapping closed mid-walk: nothing to do

    def buf(self) -> memoryview:
        return memoryview(self.mm)

    def close(self):
        try:
            self.mm.close()
        except BufferError:
            pass  # zero-copy views still alive; leave mapping for process exit


class ShmClient:
    """Client-side zero-copy access to segments created by the agent-side store.
    Mirrors the reference's plasma client (plasma/client.cc) minus fd-passing:
    POSIX shm names stand in for the fds (fling.cc)."""

    def __init__(self):
        self._attached: dict[str, _MappedSegment] = {}
        self._lock = threading.Lock()

    def map(self, shm_name: str, size: int, offset: int = 0) -> memoryview:
        with self._lock:
            seg = self._attached.get(shm_name)
            if seg is None:
                seg = self._attached[shm_name] = _MappedSegment(shm_name)
        return seg.buf()[offset:offset + size]

    def write(self, shm_name: str, size: int, writer, offset: int = 0) -> None:
        """``writer(memoryview)`` fills the buffer."""
        mv = self.map(shm_name, size, offset)
        writer(mv)

    def release(self, shm_name: str):
        with self._lock:
            seg = self._attached.pop(shm_name, None)
        if seg is not None:
            seg.close()

    def close(self):
        with self._lock:
            segs, self._attached = list(self._attached.values()), {}
        for seg in segs:
            seg.close()


class NativeShmStore:
    """Agent-side store backed by the C++ arena allocator
    (ray_tpu/_native/shm_store.cc): ONE shm segment per node, objects are
    [offset, size) extents handed out by a best-fit free list, LRU eviction in
    native code. Clients mmap the arena once and read every object zero-copy
    at its offset — same client model as plasma's single memory-mapped pool
    (plasma/client.cc), with (arena_name, offset) standing in for fd-passing.

    Same interface as ShmStore; selected by config.use_native_object_store
    when the toolchain can build the library.
    """

    def __init__(self, capacity_bytes: int, prefix: str = "rtpu"):
        import ctypes
        import os

        from ray_tpu import _native

        lib = _native.load_library()
        if lib is None:
            raise RuntimeError(
                f"native store unavailable: {_native.build_error()!r}")
        self._ctypes = ctypes
        self._lib = lib
        self.capacity = capacity_bytes
        self.arena_name = f"{prefix}_arena_{os.getpid()}"
        self._handle = lib.rtpu_store_create(
            self.arena_name.encode(), ctypes.c_uint64(capacity_bytes))
        if not self._handle:
            raise RuntimeError("native store arena creation failed")
        self._base = lib.rtpu_store_base(ctypes.c_void_p(self._handle))
        self._lock = threading.Lock()
        # same-process writers (driver in head mode, in-proc workers) write
        # through THIS mapping instead of creating their own: the arena's
        # pages are materialized here by the C++ pre-toucher, while a fresh
        # per-client mmap pays a minor fault per 4 KiB (measured 1.6 vs
        # 5.6+ GB/s on the dev box)
        self._views_handed = False
        with _ARENA_LOCK:
            _LOCAL_ARENAS[self.arena_name] = self
        self._hints: dict[ObjectID, str] = {}
        # reused under self._lock: avoids a 64KB alloc+memset per put
        self._evicted_buf = ctypes.create_string_buffer(1 << 16)
        self.num_evicted = 0
        self.on_evict = None

    def _drain_evictions(self) -> list[ObjectID]:
        """Parse newline-separated hex ids out of the (truncation-safe)
        eviction buffer; must hold self._lock."""
        raw = self._evicted_buf.value
        if not raw:
            return []
        out = []
        for hexid in raw.decode().split("\n"):
            if not hexid:
                continue
            try:
                oid = ObjectID(bytes.fromhex(hexid))
            except ValueError:
                continue  # defensive: never fail a put on a bad notice
            self._hints.pop(oid, None)
            out.append(oid)
        return out

    def _notify_evicted(self, oids: list[ObjectID]) -> None:
        for oid in oids:
            self.num_evicted += 1
            if self.on_evict is not None:
                try:
                    self.on_evict(oid)
                except Exception:
                    pass

    def create(self, object_id: ObjectID, size: int,
               device_hint: str = "") -> tuple[str, int]:
        ct = self._ctypes
        offset = ct.c_uint64()
        with self._lock:
            self._evicted_buf[0] = b"\x00"
            rc = self._lib.rtpu_store_put(
                ct.c_void_p(self._handle), object_id.hex().encode(),
                ct.c_uint64(size), ct.byref(offset), self._evicted_buf,
                ct.c_uint64(len(self._evicted_buf)))
            if rc == 0 and device_hint:
                self._hints[object_id] = device_hint
            evicted = self._drain_evictions()
        self._notify_evicted(evicted)
        if rc == -2:
            raise ObjectStoreFullError(
                f"object of {size} bytes does not fit in native arena "
                f"({self.capacity} capacity)")
        return self.arena_name, offset.value

    def seal(self, object_id: ObjectID):
        rc = self._lib.rtpu_store_seal(
            self._ctypes.c_void_p(self._handle), object_id.hex().encode())
        if rc != 0:
            raise KeyError(f"seal of unknown object {object_id}")

    def _get(self, object_id: ObjectID):
        ct = self._ctypes
        offset, size, sealed = ct.c_uint64(), ct.c_uint64(), ct.c_int()
        rc = self._lib.rtpu_store_get(
            ct.c_void_p(self._handle), object_id.hex().encode(),
            ct.byref(offset), ct.byref(size), ct.byref(sealed))
        if rc != 0:
            return None
        return offset.value, size.value, bool(sealed.value)

    def get_meta(self, object_id: ObjectID) -> tuple | None:
        """copy_on_read=True: arena extents are REUSED after LRU eviction,
        so readers must not keep aliases into the mapping (plasma solves
        this with client-side pinning, plasma/client.cc; until that
        protocol exists here, readers copy out)."""
        got = self._get(object_id)
        if got is None or not got[2]:
            return None
        return (self.arena_name, got[0], got[1],
                self._hints.get(object_id, ""), True)

    def contains(self, object_id: ObjectID) -> bool:
        got = self._get(object_id)
        return got is not None and got[2]

    def pin(self, object_id: ObjectID, pinned: bool = True):
        self._lib.rtpu_store_pin(
            self._ctypes.c_void_p(self._handle), object_id.hex().encode(),
            1 if pinned else 0)

    def delete(self, object_id: ObjectID):
        self._hints.pop(object_id, None)
        self._lib.rtpu_store_delete(
            self._ctypes.c_void_p(self._handle), object_id.hex().encode())

    def read_bytes(self, object_id: ObjectID, offset: int = 0,
                   size: int | None = None) -> tuple[int, bytes] | None:
        meta = self.get_meta(object_id)
        if meta is None:
            return None
        _name, obj_off, total = meta[0], meta[1], meta[2]
        end = total if size is None else min(total, offset + size)
        n = max(0, end - offset)
        data = self._ctypes.string_at(self._base + obj_off + offset, n)
        return total, data

    def write_bytes(self, object_id: ObjectID, data: bytes):
        _name, obj_off = self.create(object_id, len(data))
        self._ctypes.memmove(self._base + obj_off, data, len(data))
        self.seal(object_id)

    def write_chunk(self, object_id: ObjectID, offset: int, data: bytes,
                    total: int):
        """Streamed chunk write into the arena (single writer per object)."""
        _name, obj_off = self.create(object_id, total)
        self._ctypes.memmove(self._base + obj_off + offset, data, len(data))
        if offset + len(data) >= total:
            self.seal(object_id)

    def stats(self) -> dict:
        ct = self._ctypes
        used, num_obj, evicted, cap = (ct.c_uint64(), ct.c_uint64(),
                                       ct.c_uint64(), ct.c_uint64())
        with self._lock:
            if not self._handle:  # shut down concurrently (agent stop)
                return {"num_objects": 0, "used_bytes": 0,
                        "capacity_bytes": 0, "num_evicted": 0,
                        "backend": "native"}
            self._lib.rtpu_store_stats(
                ct.c_void_p(self._handle), ct.byref(used), ct.byref(num_obj),
                ct.byref(evicted), ct.byref(cap))
        return {
            "num_objects": num_obj.value,
            "used_bytes": used.value,
            "capacity_bytes": cap.value,
            "num_evicted": evicted.value,
            "backend": "native",
        }

    def local_write_view(self, offset: int, size: int):
        """Writable memoryview over [offset, offset+size) of the in-process
        arena mapping, or None once shut down. Handing out a view switches
        the arena to leak-the-mapping-at-destroy (a racing shutdown must
        not munmap under a writer mid-memcpy; pages go back at process
        exit — the same lifetime model as _MappedSegment.close)."""
        with self._lock:
            if not self._handle:
                return None
            if not self._views_handed:
                self._views_handed = True
                self._lib.rtpu_store_leak_mapping(
                    self._ctypes.c_void_p(self._handle))
            buf = (self._ctypes.c_char * size).from_address(self._base + offset)
        return memoryview(buf).cast("B")

    def shutdown(self):
        with _ARENA_LOCK:
            if _LOCAL_ARENAS.get(self.arena_name) is self:
                del _LOCAL_ARENAS[self.arena_name]
        with self._lock:
            if self._handle:
                self._lib.rtpu_store_destroy(self._ctypes.c_void_p(self._handle))
                self._handle = None


class SpillingStore:
    """Disk-spilling wrapper over either shm backend.

    TPU-native analog of the reference's LocalObjectManager spilling
    (/root/reference/src/ray/raylet/local_object_manager.h:44,
    SpillObjects:114 + SpilledObjectReader): when a create would exceed the
    high-water mark, sealed objects are spilled to local disk in LRU order
    (pinned or not — spill preserves the value, so it never changes
    semantics; a get of a spilled object restores it transparently). The
    wrapper owns ALL reclamation: every object stays backend-pinned so the
    backend's lease-blind LRU eviction can never reuse an extent under a
    live reader (see pin()).
    """

    def __init__(self, backend, spill_dir: str, capacity_bytes: int,
                 headroom: float = 0.1):
        import os

        self._b = backend
        self._dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self._capacity = capacity_bytes
        self._high_water = int(capacity_bytes * (1.0 - headroom))
        self._lock = threading.Lock()
        # our own LRU + seal view (backend internals differ); oid -> size
        self._lru: OrderedDict[ObjectID, int] = OrderedDict()
        self._sealed: set[ObjectID] = set()
        self._spilled: dict[ObjectID, int] = {}  # oid -> size on disk
        self._last_read: dict[ObjectID, float] = {}  # grace vs read races
        # READ LEASES: arena extents are reused after spill/delete, and
        # readers deserialize zero-copy over the mapping (arrow tables keep
        # aliasing it) — spilling an object mid-read segfaults the reader
        # in native code. get_meta takes a lease; the reader releases it
        # after deserializing; spill skips leased objects (expiry bounds a
        # crashed reader).
        self._read_leases: dict[ObjectID, int] = {}
        self._lease_expiry: dict[ObjectID, float] = {}
        self._pending_delete: set[ObjectID] = set()
        self.num_spilled = 0
        self.num_restored = 0

    # passthrough surface ------------------------------------------------
    @property
    def capacity(self):
        return self._capacity

    @property
    def on_evict(self):
        return self._b.on_evict

    @on_evict.setter
    def on_evict(self, fn):
        self._b.on_evict = fn

    def _spill_path(self, oid: ObjectID) -> str:
        import os
        return os.path.join(self._dir, oid.hex())

    def _maybe_spill(self, need: int) -> None:
        """Spill LRU sealed objects until `need` fits under the high-water
        mark. Lock held. Unlike eviction, spilling is safe for PINNED
        (live-ref) objects — that is its purpose (the reference spills
        primary copies under memory pressure, local_object_manager.h:44);
        a later get transparently restores. Unsealed (mid-write) objects
        are never touched."""
        used = self._b.stats()["used_bytes"]
        if used + need <= self._high_water:
            return
        now = time.monotonic()
        for oid in list(self._lru):
            if used + need <= self._high_water:
                break
            # grace window: a reader that just fetched this object's meta
            # may still be copying out of the mapping — don't pull the
            # extent out from under it (full safety needs client read
            # leases, plasma client.cc; this closes the practical window)
            if now - self._last_read.get(oid, 0.0) < 5.0:
                continue
            if self._spill_one(oid):
                used = self._b.stats()["used_bytes"]

    def _lease_active(self, oid: ObjectID) -> bool:
        """Lock held. Expired leases (crashed/lost readers — read_done is a
        best-effort notify) are swept here so they cannot leak pending
        deletes or embargo spilling forever."""
        if self._read_leases.get(oid, 0) <= 0:
            return False
        if time.monotonic() < self._lease_expiry.get(oid, 0.0):
            return True
        self._read_leases.pop(oid, None)
        self._lease_expiry.pop(oid, None)
        return False

    def _spill_one(self, oid: ObjectID) -> bool:
        """Spill one sealed object to disk. Lock held."""
        if oid not in self._sealed:
            return False
        if self._lease_active(oid):
            return False  # a reader still aliases this extent
        if oid in self._pending_delete:
            # condemned while a (now-gone) reader held it: free the memory
            # instead of wasting disk I/O on a dead object
            self._pending_delete.discard(oid)
            self._drop_locked(oid)
            return True
        out = self._b.read_bytes(oid)
        if out is None:
            self._lru.pop(oid, None)
            return False
        _total, data = out
        with open(self._spill_path(oid), "wb") as f:
            f.write(data)
        self._b.delete(oid)
        self._spilled[oid] = len(data)
        self._lru.pop(oid, None)
        self.num_spilled += 1
        return True

    def _restore(self, oid: ObjectID) -> bool:
        """Bring a spilled object back into shm. Lock held."""
        import os
        path = self._spill_path(oid)
        size = self._spilled.get(oid)
        if size is None or not os.path.exists(path):
            return False
        self._maybe_spill(size)
        with open(path, "rb") as f:
            data = f.read()
        self._alloc_with_forced_spill(
            lambda: self._b.write_bytes(oid, data), size, exclude=oid)
        # stays backend-pinned (see pin()): reclamation is wrapper-only
        self._lru[oid] = size
        self._sealed.add(oid)
        self._spilled.pop(oid, None)
        os.remove(path)
        self.num_restored += 1
        return True

    def _alloc_with_forced_spill(self, attempt, size: int, exclude=None):
        """Run an allocating backend op, force-spilling LRU objects one at
        a time on ObjectStoreFullError (grace-window skips or arena
        fragmentation must grind through disk, not fail the task). Lock
        held. Raises only when the op can never fit or nothing is left to
        spill."""
        while True:
            try:
                return attempt()
            except ObjectStoreFullError:
                if size > self._high_water:
                    raise  # spilling can never make this fit
                spilled = False
                for oid in list(self._lru):
                    if oid != exclude and self._spill_one(oid):
                        spilled = True
                        break
                if not spilled:
                    raise

    def _drop_locked(self, oid: ObjectID):
        """Forget an object entirely (lock held)."""
        import os
        self._lru.pop(oid, None)
        self._sealed.discard(oid)
        self._last_read.pop(oid, None)
        if self._spilled.pop(oid, None) is not None:
            try:
                os.remove(self._spill_path(oid))
            except OSError:
                pass
        self._b.delete(oid)

    # store interface ----------------------------------------------------
    def create(self, object_id: ObjectID, size: int, device_hint: str = ""):
        with self._lock:
            self._maybe_spill(size)
            name_off = self._alloc_with_forced_spill(
                lambda: self._b.create(object_id, size, device_hint), size)
            self._lru[object_id] = size
            return name_off

    def seal(self, object_id: ObjectID):
        self._b.seal(object_id)
        with self._lock:
            self._sealed.add(object_id)

    def get_meta(self, object_id: ObjectID):
        with self._lock:
            meta = self._b.get_meta(object_id)
            if meta is None and object_id in self._spilled:
                if self._restore(object_id):
                    meta = self._b.get_meta(object_id)
            if meta is not None:
                self._lru.move_to_end(object_id, last=True)
                self._last_read[object_id] = time.monotonic()
                # read lease: the caller will map/alias this extent; it
                # must not be spilled until read_done (expiry backstops a
                # crashed reader)
                self._read_leases[object_id] = \
                    self._read_leases.get(object_id, 0) + 1
                # expiry scales with size: copy-out + deserialize of a
                # GiB-scale object on a busy host can exceed a flat minute
                self._lease_expiry[object_id] = time.monotonic() + 60.0 + \
                    meta[2] / (16 * 1024 * 1024)
            return meta

    def read_done(self, object_id: ObjectID):
        """Reader finished deserializing: release one read lease (and apply
        a deletion that arrived mid-read)."""
        do_delete = False
        with self._lock:
            n = self._read_leases.get(object_id, 0)
            if n <= 1:
                self._read_leases.pop(object_id, None)
                self._lease_expiry.pop(object_id, None)
                do_delete = object_id in self._pending_delete
            else:
                self._read_leases[object_id] = n - 1
        if do_delete:
            self._pending_delete.discard(object_id)
            self.delete(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        return self._b.contains(object_id) or object_id in self._spilled

    def pin(self, object_id: ObjectID, pinned: bool = True):
        """Deliberately INERT under spilling. The backend must never see
        unpinned objects: its internal LRU eviction reuses extents without
        consulting our read leases, which tore buffers under live remote
        reads (libarrow segfaults parsing the corrupt copy). With every
        object backend-pinned, ALL reclamation flows through this
        wrapper's spill/delete, which honor leases — and spilling pinned
        objects is safe by design, so pin state doesn't gate anything."""

    def delete(self, object_id: ObjectID):
        with self._lock:
            if self._lease_active(object_id):
                # a reader is mid-copy over the extent: freeing it now
                # would reuse the memory under the copy (torn buffer) —
                # defer to read_done / the expiry sweep in _spill_one
                self._pending_delete.add(object_id)
                return
            self._pending_delete.discard(object_id)
            self._drop_locked(object_id)

    def read_bytes(self, object_id: ObjectID, offset: int = 0,
                   size: int | None = None):
        out = self._b.read_bytes(object_id, offset, size)
        if out is not None:
            return out
        with self._lock:
            if object_id in self._spilled and self._restore(object_id):
                return self._b.read_bytes(object_id, offset, size)
        return None

    def write_bytes(self, object_id: ObjectID, data: bytes):
        with self._lock:
            self._maybe_spill(len(data))
            self._alloc_with_forced_spill(
                lambda: self._b.write_bytes(object_id, data), len(data))
            self._lru[object_id] = len(data)
            self._sealed.add(object_id)

    def write_chunk(self, object_id: ObjectID, offset: int, data: bytes,
                    total: int):
        if offset == 0:
            with self._lock:
                self._maybe_spill(total)
                # first chunk allocates the extent: grind through spill on
                # pressure like every other allocating path
                self._alloc_with_forced_spill(
                    lambda: self._b.write_chunk(object_id, offset, data,
                                                total), total)
        else:
            self._b.write_chunk(object_id, offset, data, total)
        with self._lock:
            self._lru[object_id] = total
            if offset + len(data) >= total:
                self._sealed.add(object_id)

    def stats(self) -> dict:
        out = self._b.stats()
        out["num_spilled"] = self.num_spilled
        out["num_restored"] = self.num_restored
        out["spilled_bytes"] = sum(self._spilled.values())
        return out

    def shutdown(self):
        import shutil as _sh
        self._b.shutdown()
        _sh.rmtree(self._dir, ignore_errors=True)


def make_store(capacity_bytes: int, prefix: str = "rtpu"):
    """Pick the store backend per config.use_native_object_store (falling
    back to the pure-python per-object-segment store when the native library
    cannot be built), wrapped with disk spilling when enabled."""
    import os

    from ray_tpu.core.config import get_config

    cfg = get_config()
    backend = None
    if cfg.use_native_object_store:
        try:
            backend = NativeShmStore(capacity_bytes, prefix)
        except Exception as e:
            import logging
            logging.getLogger(__name__).warning(
                "native object store unavailable (%s); falling back to the "
                "pure-python store", e)
    if backend is None:
        backend = ShmStore(capacity_bytes, prefix)
    if cfg.enable_object_spilling:
        spill_dir = os.path.join(cfg.spill_dir or "/tmp/ray_tpu_spill",
                                 prefix)
        return SpillingStore(backend, spill_dir, capacity_bytes)
    return backend
