"""Dataset: the public lazy, streaming dataset API.

TPU-native analog of the reference's Dataset
(/root/reference/python/ray/data/dataset.py — map_batches, iter_batches:4965,
streaming_split:1818, groupby, sort, union/zip, write_*) built on the logical
plan (ray_tpu.data.logical) and streaming executor (ray_tpu.data.executor).
Execution is lazy: transforms append logical ops; iteration/consumption runs
the optimized plan with streaming backpressure.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.executor import StreamingExecutor
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.logical import (
    Aggregate,
    Filter,
    FlatMap,
    InputData,
    Join,
    Limit,
    LogicalOp,
    LogicalPlan,
    MapBatches,
    MapRows,
    RandomShuffle,
    Read,
    Repartition,
    Sort,
    Union,
    Write,
    Zip,
)


class Dataset:
    def __init__(self, terminal: LogicalOp, parallelism: int = 8):
        self._terminal = terminal
        self._parallelism = parallelism

    # ---- plan building ---------------------------------------------------
    def _with(self, op: LogicalOp) -> "Dataset":
        return Dataset(op, self._parallelism)

    def map_batches(self, fn, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy", compute: Optional[str] = None,
                    num_cpus: Optional[float] = None,
                    resources: Optional[dict] = None,
                    concurrency: Optional[int] = None,
                    fn_args: tuple = (), fn_kwargs: Optional[dict] = None,
                    fn_constructor_args: tuple = ()) -> "Dataset":
        is_class = isinstance(fn, type)
        res = dict(resources or {})
        if num_cpus:
            res["CPU"] = num_cpus
        return self._with(MapBatches(
            name=f"MapBatches({_fn_name(fn)})", inputs=[self._terminal],
            fn=fn, fn_args=fn_args, fn_kwargs=fn_kwargs or {},
            batch_size=batch_size, batch_format=batch_format,
            compute="actors" if (compute == "actors" or is_class) else "tasks",
            num_actors=concurrency or 2, resources=res,
            fn_constructor_args=fn_constructor_args))

    def map(self, fn, **kwargs) -> "Dataset":
        return self._with(MapRows(name=f"Map({_fn_name(fn)})",
                                  inputs=[self._terminal], fn=fn,
                                  compute="actors" if isinstance(fn, type) else "tasks"))

    def flat_map(self, fn, **kwargs) -> "Dataset":
        return self._with(FlatMap(name=f"FlatMap({_fn_name(fn)})",
                                  inputs=[self._terminal], fn=fn))

    def filter(self, fn, **kwargs) -> "Dataset":
        from ray_tpu.data.expressions import Expr
        if isinstance(fn, Expr):
            return self.filter_expr(fn)
        return self._with(Filter(name=f"Filter({_fn_name(fn)})",
                                 inputs=[self._terminal], fn=fn))

    def filter_expr(self, expr) -> "Dataset":
        """Vectorized filter from a column expression (reference
        expressions.py col/lit): evaluates per pyarrow batch — no per-row
        python — and, being a stateless batch transform, fuses into the
        read stage (logical.FusedRead pushdown)."""
        def apply(batch):
            import pyarrow as pa
            mask = expr.eval_batch(batch)
            if isinstance(batch, pa.RecordBatch):
                batch = pa.Table.from_batches([batch])
            return batch.filter(mask)
        return self._with(MapBatches(
            name=f"FilterExpr({expr!r})", inputs=[self._terminal],
            fn=apply, batch_format="pyarrow"))

    def with_column(self, name: str, expr) -> "Dataset":
        """Add/replace a column from an expression (reference
        Dataset.with_column), vectorized over pyarrow batches."""
        from ray_tpu.data.expressions import Expr, lit
        if not isinstance(expr, Expr):
            if callable(expr):  # batch -> column fn: the add_column shape
                return self.add_column(name, expr)
            expr = lit(expr)  # plain value: implicit literal (reference)

        def apply(batch):
            import pyarrow as pa
            value = expr.eval_batch(batch)
            if isinstance(batch, pa.RecordBatch):
                batch = pa.Table.from_batches([batch])
            if isinstance(value, pa.Scalar):  # pure-literal expression
                value = pa.array([value.as_py()] * batch.num_rows)
            if name in batch.column_names:
                batch = batch.drop_columns([name])
            return batch.append_column(name, value)
        return self._with(MapBatches(
            name=f"WithColumn({name})", inputs=[self._terminal],
            fn=apply, batch_format="pyarrow"))

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch: dict):
            batch[name] = fn(batch)
            return batch
        return self._with(MapBatches(name=f"AddColumn({name})",
                                     inputs=[self._terminal], fn=add))

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def drop(batch):
            return BlockAccessor.for_block(batch).drop(cols)
        return self._with(MapBatches(name="DropColumns",
                                     inputs=[self._terminal], fn=drop,
                                     batch_format="pyarrow"))

    def select_columns(self, cols: list[str]) -> "Dataset":
        def select(batch):
            return BlockAccessor.for_block(batch).select(cols)
        return self._with(MapBatches(name="SelectColumns",
                                     inputs=[self._terminal], fn=select,
                                     batch_format="pyarrow"))

    def rename_columns(self, mapping: dict[str, str]) -> "Dataset":
        def rename(batch):
            return BlockAccessor.for_block(batch).rename(mapping)
        return self._with(MapBatches(name="RenameColumns",
                                     inputs=[self._terminal], fn=rename,
                                     batch_format="pyarrow"))

    def limit(self, n: int) -> "Dataset":
        return self._with(Limit(name=f"Limit({n})", inputs=[self._terminal],
                                limit=n))

    def repartition(self, num_blocks: int, *,
                    key: Optional[str] = None) -> "Dataset":
        """Redistribute into ``num_blocks`` blocks. With ``key``, rows are
        HASH-partitioned on that column (all rows with equal keys land in
        the same output block — the distributed hash shuffle, reference:
        _internal/execution/operators/hash_shuffle.py); otherwise blocks are
        rebalanced round-robin."""
        return self._with(Repartition(name="Repartition",
                                      inputs=[self._terminal],
                                      num_blocks=num_blocks, key=key))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(RandomShuffle(name="RandomShuffle",
                                        inputs=[self._terminal], seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(Sort(name="Sort", inputs=[self._terminal], key=key,
                               descending=descending))

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.grouped import GroupedData
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(Union(name="Union",
                                inputs=[self._terminal] +
                                [o._terminal for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(Zip(name="Zip",
                              inputs=[self._terminal, other._terminal]))

    def join(self, other: "Dataset", on: str, *, right_on: str | None = None,
             how: str = "inner", num_partitions: int = 0) -> "Dataset":
        """Distributed hash join (reference dataset.py join / execution
        operators/join.py). how: inner | left_outer | right_outer |
        full_outer."""
        how = how.replace("_", " ")
        if how not in ("inner", "left outer", "right outer", "full outer"):
            raise ValueError(f"unsupported join type: {how!r}")
        return self._with(Join(
            name="Join", inputs=[self._terminal, other._terminal],
            on=on, right_on=right_on, how=how,
            num_partitions=num_partitions))

    # ---- execution -------------------------------------------------------
    def _execute(self) -> Iterator[tuple]:
        ex = StreamingExecutor(LogicalPlan(self._terminal), self._parallelism)
        self._last_executor = ex
        return ex.run()

    def iter_internal_ref_bundles(self) -> Iterator[tuple]:
        return self._execute()

    def _block_iter(self) -> Iterator[Block]:
        for ref, meta in self._execute():
            yield ray_tpu.get(ref)

    def materialize(self) -> "MaterializedDataset":
        bundles = list(self._execute())
        return MaterializedDataset(
            InputData(name="Input", bundles=bundles), self._parallelism)

    # ---- consumption -----------------------------------------------------
    def iterator(self) -> DataIterator:
        return DataIterator(self._block_iter)

    def iter_rows(self) -> Iterator[dict]:
        return self.iterator().iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return self.iterator().iter_batches(**kwargs)

    def iter_jax_batches(self, **kwargs) -> Iterator[dict]:
        return self.iterator().iter_jax_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[dict]:
        return self.iterator().iter_torch_batches(**kwargs)

    def take(self, limit: int = 20) -> list[dict]:
        out = []
        for row in self.limit(limit).iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20, batch_format: str = "numpy"):
        for batch in self.limit(batch_size).iter_batches(
                batch_size=batch_size, batch_format=batch_format):
            return batch
        return {}

    def count(self) -> int:
        total = 0
        for _, meta in self._execute():
            total += meta.num_rows
        return total

    def schema(self):
        for ref, meta in self._execute():
            if meta.schema is not None:
                return meta.schema
        return None

    def columns(self) -> list[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def size_bytes(self) -> int:
        return sum(meta.size_bytes for _, meta in self._execute())

    def num_blocks(self) -> int:
        return sum(1 for _ in self._execute())

    # aggregates
    def _agg(self, agg_fn) -> Any:
        ds = self._with(Aggregate(name="Aggregate", inputs=[self._terminal],
                                  key=None, aggs=[agg_fn]))
        rows = ds.take_all()
        if not rows:
            return None
        val = rows[0][agg_fn.out_name()]
        return val

    def aggregate(self, *aggs) -> dict:
        """Run several aggregations in ONE pass over the dataset
        (reference Dataset.aggregate); returns {out_name: value}."""
        ds = self._with(Aggregate(name="Aggregate", inputs=[self._terminal],
                                  key=None, aggs=list(aggs)))
        rows = ds.take_all()
        if not rows:
            return {}
        return {a.out_name(): rows[0][a.out_name()] for a in aggs}

    def sum(self, on: str):
        return self._agg(agg_mod.Sum(on))

    def min(self, on: str):
        return self._agg(agg_mod.Min(on))

    def max(self, on: str):
        return self._agg(agg_mod.Max(on))

    def mean(self, on: str):
        return self._agg(agg_mod.Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self._agg(agg_mod.Std(on, ddof))

    # ---- splits ----------------------------------------------------------
    def split(self, n: int) -> list["MaterializedDataset"]:
        bundles = list(self._execute())
        shards: list[list] = [[] for _ in range(n)]
        # greedy row balancing
        order = sorted(bundles, key=lambda b: -b[1].num_rows)
        loads = [0] * n
        for b in order:
            i = loads.index(min(loads))
            shards[i].append(b)
            loads[i] += b[1].num_rows
        return [MaterializedDataset(InputData(name="Input", bundles=s),
                                    self._parallelism) for s in shards]

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> list[DataIterator]:
        """N coordinated iterators, one per consumer (reference
        dataset.py:1818). A coordinator actor runs the executor and deals
        bundles; consumers (train workers, possibly in other processes) pull
        blocks through actor calls.

        Re-iterating an iterator starts a new epoch: the coordinator re-runs
        the executor, so multi-epoch training loops work. With ``equal=True``
        every rank receives the same number of blocks AND the same number of
        rows per epoch (row-level tail equalization like the reference's
        output_splitter.py; up to n-1 remainder rows are dropped), which keeps
        SPMD collectives deadlock-free.
        """
        coord = _SplitCoordinator.options(
            max_concurrency=max(4, 2 * n + 1)).remote(
            self._terminal, self._parallelism, n, equal)

        def make_factory(rank: int):
            epoch = [0]

            def factory():
                e = epoch[0]
                epoch[0] += 1
                while True:
                    blk = ray_tpu.get(coord.next.remote(rank, e), timeout=120.0)
                    if blk is None:
                        return
                    yield blk
            return factory

        return [DataIterator(make_factory(i)) for i in range(n)]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        mat = ds.materialize()
        total = mat.count()
        n_test = int(total * test_size)
        rows = mat.take_all()
        from ray_tpu.data.read_api import from_items
        return (from_items(rows[: total - n_test]),
                from_items(rows[total - n_test:]))

    # ---- writes ----------------------------------------------------------
    def _write(self, path: str, fmt: str) -> list[str]:
        ds = self._with(Write(name="Write", inputs=[self._terminal],
                              path=path, file_format=fmt))
        paths = []
        for ref, meta in ds._execute():
            blk = ray_tpu.get(ref)
            paths.extend(BlockAccessor.for_block(blk).column_to_numpy("path").tolist())
        return paths

    def write_parquet(self, path: str) -> list[str]:
        return self._write(path, "parquet")

    def write_csv(self, path: str) -> list[str]:
        return self._write(path, "csv")

    def write_json(self, path: str) -> list[str]:
        return self._write(path, "json")

    # ---- misc ------------------------------------------------------------
    def stats(self) -> str:
        """Execution statistics of the last run (reference Dataset.stats /
        _internal/stats.py): per-op blocks/rows/bytes/wall time. Before any
        execution, shows the optimized plan."""
        from ray_tpu.data.logical import LogicalPlan as LP, optimize
        plan = f"Plan: {optimize(LP(self._terminal))}"
        ex = getattr(self, "_last_executor", None)
        if ex is None:
            return plan
        return f"{plan}\n{ex.stats_summary()}"

    def __repr__(self):
        return f"Dataset(plan={LogicalPlan(self._terminal)})"

    def __iter__(self):
        return self.iter_rows()


class MaterializedDataset(Dataset):
    """A dataset whose blocks are already in the object store."""

    @property
    def bundles(self) -> list:
        return self._terminal.bundles


@ray_tpu.remote
class _SplitCoordinator:
    """Runs the executor once per epoch and deals bundles to n consumers.

    equal=True deals fixed-size rounds (one block to every rank per round,
    equal rows per block) with row-level equalization at the tail, mirroring
    the reference's output_splitter.py guarantee that ranks receive equal row
    counts. Each epoch re-runs the executor, so iterators are re-iterable.
    """

    def __init__(self, terminal, parallelism: int, n: int, equal: bool = True):
        import threading as th

        self._terminal = terminal
        self._parallelism = parallelism
        self._n = n
        self._equal = equal
        self._lock = th.Lock()
        self._epochs: dict[int, list] = {}
        self._finished_ranks: dict[int, set] = {}  # epoch -> ranks done

    def _queues_for(self, epoch: int, rank: int) -> list:
        import queue as queuelib
        import threading as th

        to_gc = []
        with self._lock:
            # a rank asking for epoch e has abandoned every earlier epoch
            # (early-exit consumers): count it done there so abandoned
            # epochs get collected instead of leaking pumps + executors
            for e in list(self._finished_ranks):
                if e < epoch and rank not in self._finished_ranks[e]:
                    self._finished_ranks[e].add(rank)
                    if len(self._finished_ranks[e]) >= self._n:
                        to_gc.append(e)
            if epoch not in self._epochs:
                queues = [queuelib.Queue(maxsize=4) for _ in range(self._n)]
                ex_box: list = []
                t = th.Thread(target=self._pump, args=(queues, ex_box),
                              daemon=True)
                self._epochs[epoch] = (queues, ex_box, t)
                self._finished_ranks[epoch] = set()
                t.start()
            queues = self._epochs[epoch][0]
        for e in to_gc:
            self._gc_epoch(e)
        return queues

    def _mark_done(self, epoch: int, rank: int) -> None:
        # GC an epoch only once EVERY rank consumed its end-of-stream
        # sentinel (or moved on); dropping earlier would strand a lagging
        # rank on orphaned queues (and re-running the executor would hand it
        # duplicate rows).
        gc = False
        with self._lock:
            done = self._finished_ranks.get(epoch)
            if done is None:
                return
            done.add(rank)
            gc = len(done) >= self._n
        if gc:
            self._gc_epoch(epoch)

    def _gc_epoch(self, epoch: int) -> None:
        import queue as queuelib

        with self._lock:
            entry = self._epochs.pop(epoch, None)
            self._finished_ranks.pop(epoch, None)
        if entry is None:
            return
        queues, ex_box, pump_thread = entry
        # stop the executor first (bounds what the pump can still emit),
        # then keep draining until the pump thread actually exits — it can
        # only be blocked on queue.put, and every drain frees capacity
        for ex in ex_box:
            try:
                ex.stop()
            except Exception:
                pass
        import time as _time
        deadline = _time.monotonic() + 30.0
        while pump_thread.is_alive() and _time.monotonic() < deadline:
            for q in queues:
                while True:
                    try:
                        q.get_nowait()
                    except queuelib.Empty:
                        break
            _time.sleep(0.02)

    def _pump(self, queues: list, ex_box: list | None = None) -> None:
        n = self._n
        try:
            ex = StreamingExecutor(LogicalPlan(self._terminal),
                                   self._parallelism)
            if ex_box is not None:
                ex_box.append(ex)
            if not self._equal:
                for i, (ref, meta) in enumerate(ex.run()):
                    queues[i % n].put(ray_tpu.get(ref))
                return
            # equal=True: deal rounds of `chunk` rows to every rank.
            pending: list = []
            pending_rows = 0
            chunk = 0
            for ref, meta in ex.run():
                blk = ray_tpu.get(ref)
                if blk.num_rows == 0:
                    continue
                if chunk == 0:
                    chunk = blk.num_rows
                pending.append(blk)
                pending_rows += blk.num_rows
                while pending_rows >= n * chunk:
                    for q in queues:
                        q.put(_take_rows(pending, chunk))
                    pending_rows -= n * chunk
            tail = pending_rows // n
            if tail:
                for q in queues:
                    q.put(_take_rows(pending, tail))
        finally:
            for q in queues:
                q.put(None)

    def next(self, rank: int, epoch: int = 0):
        item = self._queues_for(epoch, rank)[rank].get(timeout=110.0)
        if item is None:
            self._mark_done(epoch, rank)
        return item


def _take_rows(pending: list, k: int) -> Block:
    """Remove exactly k rows from the front of `pending` (a list of blocks),
    slicing the boundary block as needed, and return them as one block."""
    out = []
    need = k
    while need > 0:
        blk = pending[0]
        if blk.num_rows <= need:
            out.append(pending.pop(0))
            need -= blk.num_rows
        else:
            out.append(blk.slice(0, need))
            pending[0] = blk.slice(need, blk.num_rows - need)
            need = 0
    return out[0] if len(out) == 1 else BlockAccessor.concat(out)


def _fn_name(fn) -> str:
    return getattr(fn, "__name__", type(fn).__name__)
