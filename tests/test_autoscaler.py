"""Autoscaler tests (reference: autoscaler/v2 + fake_multi_node provider —
scale-up on unplaceable demand, scale-down on idle timeout, all without a
cloud)."""

import time

import pytest

import ray_tpu


def test_autoscaler_scale_up_and_down():
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # head-ish node, stays
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(cluster.control_plane.addr)
    scaler = Autoscaler(
        cluster.control_plane.addr, provider,
        AutoscalerConfig(min_workers=0, max_workers=2,
                         node_resources={"CPU": 1, "accel": 1},
                         idle_timeout_s=1.0))
    try:
        # demand an actor needing a resource only autoscaled nodes provide
        @ray_tpu.remote(resources={"accel": 1})
        class A:
            def m(self):
                return "on-accel-node"

        a = A.remote()
        time.sleep(0.3)  # let the actor become pending demand
        scaler.update()
        assert provider.non_terminated_nodes(), "no node launched"
        assert ray_tpu.get(a.m.remote(), timeout=60) == "on-accel-node"
        assert scaler.num_launched == 1

        # release the demand; node should terminate after idle timeout
        ray_tpu.kill(a)
        deadline = time.monotonic() + 30
        while provider.non_terminated_nodes() and time.monotonic() < deadline:
            time.sleep(0.5)
            scaler.update()
        assert not provider.non_terminated_nodes(), "idle node not reclaimed"
        assert scaler.num_terminated == 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_autoscaler_e2e_real_loop():
    """End-to-end through the STARTED reconciliation loop (not manual
    update() calls): demand -> launches -> actors run on scaled nodes ->
    idle -> terminations, with launch/terminate sequence assertions.
    Scaled nodes host in-process workers (fake_multi_node-style harness)."""
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(cluster.control_plane.addr,
                                inproc_workers=True)
    scaler = Autoscaler(
        cluster.control_plane.addr, provider,
        AutoscalerConfig(min_workers=0, max_workers=3,
                         node_resources={"CPU": 1, "accel": 1},
                         idle_timeout_s=1.0, poll_interval_s=0.2))
    scaler.start()
    try:
        @ray_tpu.remote(resources={"accel": 1})
        class W:
            def ping(self):
                return "up"

        actors = [W.remote() for _ in range(2)]
        assert ray_tpu.get([a.ping.remote() for a in actors],
                           timeout=120) == ["up", "up"]
        assert scaler.num_launched == 2  # one launch per unplaceable actor
        assert len(provider.non_terminated_nodes()) == 2

        for a in actors:
            ray_tpu.kill(a)
        deadline = time.monotonic() + 60
        while provider.non_terminated_nodes() and time.monotonic() < deadline:
            time.sleep(0.3)
        assert not provider.non_terminated_nodes(), "idle nodes not reclaimed"
        assert scaler.num_terminated == 2
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_autoscaler_slice_scale_up_and_down():
    """A slice-shaped (multi-host) PG request scales up ONE provider node
    that registers as multiple CP hosts sharing a slice_name, the slice PG
    places atomically on it, and removal scales the WHOLE slice down."""
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(cluster.control_plane.addr,
                                inproc_workers=True)
    scaler = Autoscaler(
        cluster.control_plane.addr, provider,
        AutoscalerConfig(min_workers=0, max_workers=2,
                         node_resources={"CPU": 2, "TPU": 4},
                         node_labels={"pod_type": "v5p-8"},
                         hosts_per_node=2,
                         idle_timeout_s=1.0, poll_interval_s=0.2))
    scaler.start()
    try:
        pg = ray_tpu.tpu_slice_placement_group("v5p-8")  # 2 hosts x 4 chips
        assert pg.ready(timeout=120.0), "slice PG never placed"
        assert len(provider.non_terminated_nodes()) == 1  # ONE slice launch
        assert scaler.num_launched == 1
        # the slice registered as 2 CP hosts sharing one slice_name
        slice_nodes = [n for n in ray_tpu.nodes()
                       if (n.get("labels") or {}).get("provider_node_name")]
        assert len(slice_nodes) == 2
        assert len({n["labels"]["slice_name"] for n in slice_nodes}) == 1

        ray_tpu.remove_placement_group(pg)
        deadline = time.monotonic() + 60
        while provider.non_terminated_nodes() and time.monotonic() < deadline:
            time.sleep(0.3)
        assert not provider.non_terminated_nodes(), "idle slice not reclaimed"
        assert scaler.num_terminated == 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_autoscaler_respects_max_workers():
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(cluster.control_plane.addr)
    scaler = Autoscaler(
        cluster.control_plane.addr, provider,
        AutoscalerConfig(max_workers=1, node_resources={"CPU": 1, "gp": 1}))
    try:
        @ray_tpu.remote(resources={"gp": 1})
        class B:
            def m(self):
                return 1

        actors = [B.remote() for _ in range(4)]  # demand for 4 nodes
        time.sleep(0.3)
        for _ in range(3):
            scaler.update()
        assert len(provider.non_terminated_nodes()) == 1  # capped
        assert ray_tpu.get(actors[0].m.remote(), timeout=60) == 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_instance_manager_state_machine():
    """The v2 lifecycle: QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING ->
    TERMINATING -> TERMINATED, with transition history recorded; failed
    creates land in ALLOCATION_FAILED (reference: autoscaler/v2
    instance_manager reconciliation)."""
    from ray_tpu.autoscaler.instance_manager import (InstanceManager,
                                                     InstanceState)

    class Prov:
        def __init__(self):
            self.nodes = []
            self.fail_next = False
            self.n = 0

        def create_node(self, cfg):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("quota exceeded")
            self.n += 1
            name = f"node-{self.n}"
            self.nodes.append(name)
            return name

        def terminate_node(self, name):
            self.nodes.remove(name)

        def non_terminated_nodes(self):
            return list(self.nodes)

    prov = Prov()
    im = InstanceManager(prov)
    registered: set = set()

    inst = im.queue_launch({"resources": {"CPU": 1}})
    assert inst.state == InstanceState.QUEUED
    im.reconcile(lambda n: n in registered)
    assert inst.state == InstanceState.ALLOCATED and inst.name == "node-1"
    # still booting
    im.reconcile(lambda n: n in registered)
    assert inst.state == InstanceState.ALLOCATED
    registered.add("node-1")
    im.reconcile(lambda n: n in registered)
    assert inst.state == InstanceState.RAY_RUNNING

    # terminate path
    assert im.begin_terminate("node-1", "idle")
    assert inst.state == InstanceState.TERMINATING
    im.reconcile(lambda n: n in registered)
    assert inst.state == InstanceState.TERMINATED
    # full audit trail
    states = [b for _, _, b, _ in inst.history]
    assert states == ["QUEUED", "REQUESTED", "ALLOCATED", "RAY_RUNNING",
                      "TERMINATING", "TERMINATED"]

    # allocation failure
    prov.fail_next = True
    bad = im.queue_launch({})
    im.reconcile(lambda n: False)
    assert bad.state == InstanceState.ALLOCATION_FAILED
    assert "quota" in bad.history[-1][3]
    assert im.summary()["ALLOCATION_FAILED"] == 1


def test_instance_manager_terminate_retry_and_adoption():
    from ray_tpu.autoscaler.instance_manager import (InstanceManager,
                                                     InstanceState)

    class FlakyProv:
        def __init__(self):
            self.nodes = ["adopted-1"]
            self.fails = 1

        def create_node(self, cfg):
            raise AssertionError("not used")

        def terminate_node(self, name):
            if self.fails:
                self.fails -= 1
                raise RuntimeError("gcloud 503")
            self.nodes.remove(name)

        def non_terminated_nodes(self):
            return list(self.nodes)

    im = InstanceManager(FlakyProv())
    # node launched before the manager existed: reconcile adopts it, and a
    # flaked terminate rolls back to the ACTUAL prior state (retryable)
    im.reconcile(lambda n: True)
    inst = im.by_name("adopted-1")
    assert inst.state == InstanceState.RAY_RUNNING  # adopted + registered
    assert not im.begin_terminate("adopted-1", "idle")  # first call flakes
    assert inst.state == InstanceState.RAY_RUNNING  # rolled back to prior
    assert im.begin_terminate("adopted-1", "idle retry")
    im.reconcile(lambda n: True)
    assert inst.state == InstanceState.TERMINATED


def test_autoscaler_tracks_instances(ray_start_regular):
    """The live autoscaler records every provider node as an instance with
    lifecycle history (dashboard/audit surface)."""
    import ray_tpu
    from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
    from ray_tpu.autoscaler.instance_manager import InstanceState
    from ray_tpu.autoscaler.node_provider import FakeNodeProvider
    from ray_tpu.core import api

    rt = api._get_runtime()
    provider = FakeNodeProvider(rt.cp_addr, inproc_workers=True)
    scaler = Autoscaler(
        rt.cp_addr, provider,
        AutoscalerConfig(min_workers=1, max_workers=2,
                         node_resources={"CPU": 1},
                         idle_timeout_s=300.0))
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            scaler.update()
            insts = scaler.instance_manager.instances()
            if insts and insts[0].state == InstanceState.RAY_RUNNING:
                break
            time.sleep(0.5)
        assert insts and insts[0].state == InstanceState.RAY_RUNNING
        assert [b for _, _, b, _ in insts[0].history][:3] == \
            ["QUEUED", "REQUESTED", "ALLOCATED"]
    finally:
        for name in provider.non_terminated_nodes():
            provider.terminate_node(name)


def _install_fake_kubectl(tmp_path, monkeypatch):
    """Fake kubectl on PATH recording every invocation and serving canned
    pod listings. Mirrors the real verb semantics the provider relies on:
    `create` FAILS on a name collision (apply would silently succeed)."""
    import json
    import os
    import stat

    log = tmp_path / "kubectl.log"
    pods_file = tmp_path / "pods.json"
    pods_file.write_text(json.dumps({"items": []}))
    fake = tmp_path / "kubectl"
    fake.write_text(f"""#!/usr/bin/env python3
import json, sys
args = sys.argv[1:]
stdin = sys.stdin.read() if not sys.stdin.isatty() else ""
with open({str(log)!r}, "a") as f:
    f.write(json.dumps({{"args": args, "stdin": stdin}}) + "\\n")
state = json.load(open({str(pods_file)!r}))
if "create" in args:
    pod = json.loads(stdin)
    name = pod["metadata"]["name"]
    if any(p["metadata"]["name"] == name for p in state["items"]):
        print(f"Error from server (AlreadyExists): pods {{name!r}} "
              "already exists", file=sys.stderr)
        sys.exit(1)
    pod["status"] = {{"phase": "Running"}}
    state["items"].append(pod)
elif "delete" in args:
    name = args[args.index("pod") + 1]
    state["items"] = [p for p in state["items"]
                      if p["metadata"]["name"] != name]
elif "get" in args:
    print(json.dumps(state))
json.dump(state, open({str(pods_file)!r}, "w"))
""")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
    return log, pods_file


def test_kubernetes_provider_with_fake_kubectl(tmp_path, monkeypatch):
    """KubeRay-style provider drives kubectl correctly: pod manifests with
    resource requests + labels on create, label-selected listing, delete
    on terminate (hermetic e2e of the provider contract)."""
    import json

    log, _ = _install_fake_kubectl(tmp_path, monkeypatch)

    from ray_tpu.autoscaler.node_provider import KubernetesNodeProvider

    prov = KubernetesNodeProvider("10.0.0.1:9000", namespace="ml",
                                  image="ray-tpu:v5")
    name = prov.create_node({"resources": {"CPU": 4, "TPU": 8},
                             "labels": {"pod_type": "v5litepod-8"}})
    assert prov.non_terminated_nodes() == [name]
    prov.terminate_node(name)
    assert prov.non_terminated_nodes() == []

    calls = [json.loads(l) for l in log.read_text().splitlines()]
    create = next(c for c in calls if "create" in c["args"])
    pod = json.loads(create["stdin"])
    spec = pod["spec"]["containers"][0]
    assert spec["resources"]["requests"] == {"cpu": "4000m",
                                             "google.com/tpu": "8"}
    assert pod["metadata"]["labels"]["ray-tpu-node"] == "true"
    assert "--address" in spec["command"]
    assert "10.0.0.1:9000" in spec["command"]
    label_arg = spec["command"][spec["command"].index("--labels") + 1]
    labels = dict(item.split("=", 1) for item in label_arg.split(","))
    assert labels["provider_node_name"] == name  # CLI k=v format
    assert labels["pod_type"] == "v5litepod-8"
    # namespace threaded through every call
    assert all(c["args"][:2] == ["-n", "ml"] for c in calls)


def test_kubernetes_pod_names_unique_across_restarts(tmp_path, monkeypatch):
    """Generated pod names carry a random suffix: the per-provider counter
    resets on autoscaler restart, so a bare counter name would collide
    with a pod the previous incarnation left behind."""
    import re

    _install_fake_kubectl(tmp_path, monkeypatch)
    from ray_tpu.autoscaler.node_provider import KubernetesNodeProvider

    prov1 = KubernetesNodeProvider("10.0.0.1:9000")
    name1 = prov1.create_node({"resources": {"CPU": 1}})
    assert re.fullmatch(r"ray-tpu-worker-1-[0-9a-f]{6}", name1)

    # "restart": a fresh provider whose counter starts over must still
    # produce a distinct name while pod 1 is alive
    prov2 = KubernetesNodeProvider("10.0.0.1:9000")
    name2 = prov2.create_node({"resources": {"CPU": 1}})
    assert name2 != name1
    assert sorted(prov2.non_terminated_nodes()) == sorted([name1, name2])


def test_kubernetes_create_collision_fails_loudly(tmp_path, monkeypatch):
    """An explicit node name colliding with a leftover pod must RAISE
    (kubectl create semantics) rather than silently count phantom
    capacity (kubectl apply semantics)."""
    _install_fake_kubectl(tmp_path, monkeypatch)
    from ray_tpu.autoscaler.node_provider import KubernetesNodeProvider

    prov = KubernetesNodeProvider("10.0.0.1:9000")
    prov.create_node({"name": "pinned-name", "resources": {"CPU": 1}})
    with pytest.raises(RuntimeError, match="AlreadyExists"):
        prov.create_node({"name": "pinned-name", "resources": {"CPU": 1}})
    # the failed create added no capacity
    assert prov.non_terminated_nodes() == ["pinned-name"]


def test_autoscaler_stop_retracts_published_state(ray_start_regular):
    """stop() deletes the per-scaler autoscaler:instances:* KV key —
    otherwise every stop/start cycle leaks a key and the dashboard keeps
    showing dead instances forever."""
    from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
    from ray_tpu.autoscaler.node_provider import FakeNodeProvider
    from ray_tpu.core import api

    rt = api._get_runtime()
    provider = FakeNodeProvider(rt.cp_addr)
    scaler = Autoscaler(rt.cp_addr, provider,
                        AutoscalerConfig(min_workers=0, max_workers=1,
                                         node_resources={"CPU": 1}))
    key = f"autoscaler:instances:{scaler.scaler_id}"
    try:
        scaler._publish_state()
        assert rt.cp_client.call("kv_get", {"key": key}) is not None
    finally:
        scaler.stop()
    assert rt.cp_client.call("kv_get", {"key": key}) is None


def test_kubernetes_provider_gates_without_kubectl(monkeypatch, tmp_path):
    import shutil as _shutil

    if _shutil.which("kubectl"):
        pytest.skip("kubectl present")
    from ray_tpu.autoscaler.node_provider import KubernetesNodeProvider

    with pytest.raises(RuntimeError, match="kubectl"):
        KubernetesNodeProvider("1.2.3.4:9000")
