"""Request-robustness tests: deadline propagation, admission control,
router retries/ejection, controller health thresholds, and serve-under-chaos
(models the reference's serve fault-tolerance tests:
python/ray/serve/tests/test_failure.py + the release-test chaos suites)."""

import asyncio
import json
import threading
import time
import urllib.error
import urllib.request
import uuid

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core import deadline as request_deadline
from ray_tpu.exceptions import DeadlineExceededError, TaskError
from ray_tpu.serve.config import RouterConfig
from ray_tpu.serve.router import ReplicaSet, RetryBudget, Router


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module


@pytest.fixture
def serve_shutdown(ray_start_regular):
    yield
    serve.shutdown()


# ---- pure unit tests (no cluster) ------------------------------------------

def test_deadline_module_semantics():
    assert request_deadline.current() is None
    assert request_deadline.remaining() is None
    assert request_deadline.remaining(default=7.0) == 7.0
    assert request_deadline.bound(5.0) == 5.0
    assert not request_deadline.expired()
    request_deadline.raise_if_expired()  # no deadline: no-op

    dl = time.time() + 10.0
    with request_deadline.scope(dl):
        assert request_deadline.current() == dl
        rem = request_deadline.remaining()
        assert 9.0 < rem <= 10.0
        # bound clamps to the remaining budget
        assert request_deadline.bound(60.0) <= 10.0
        assert request_deadline.bound(1.0) == 1.0
        # scope(None) keeps the outer deadline
        with request_deadline.scope(None):
            assert request_deadline.current() == dl
        # nested scopes restore on exit
        inner = time.time() + 1.0
        with request_deadline.scope(inner):
            assert request_deadline.current() == inner
        assert request_deadline.current() == dl
    assert request_deadline.current() is None

    with request_deadline.scope(time.time() - 0.5):
        assert request_deadline.expired()
        assert request_deadline.remaining() < 0
        # non-positive budgets floor at a tiny epsilon (fail fast downstream)
        assert request_deadline.bound(30.0) == pytest.approx(0.001)
        with pytest.raises(DeadlineExceededError):
            request_deadline.raise_if_expired("unit test")


def test_task_spec_pickle_compat_without_deadline():
    """Older shorter-tuple TaskSpec pickles (pre-deadline field) must keep
    loading: trailing fields fall back to class-level defaults."""
    from ray_tpu.core.task_spec import TaskSpec

    spec = TaskSpec(name="t")
    state = spec.__getstate__()
    old_state = state[:-1]  # a spec serialized before the deadline field
    revived = TaskSpec.__new__(TaskSpec)
    revived.__setstate__(old_state)
    assert revived.name == "t"
    assert revived.deadline is None
    # current round-trip carries the deadline
    spec.deadline = 1234.5
    full = TaskSpec.__new__(TaskSpec)
    full.__setstate__(spec.__getstate__())
    assert full.deadline == 1234.5


def test_retry_budget():
    b = RetryBudget(ratio=0.5, cap=2.0)
    # starts full: a cold router may retry
    assert b.withdraw()
    assert b.withdraw()
    assert not b.withdraw()
    b.deposit()  # +0.5
    assert not b.withdraw()
    b.deposit()  # +0.5 -> 1.0
    assert b.withdraw()
    # balance is capped
    for _ in range(100):
        b.deposit()
    assert b.balance() == 2.0


class _AID:
    def __init__(self, h):
        self._h = h

    def hex(self):
        return self._h


class _FakeMethod:
    def __init__(self, replica, kind):
        self._replica = replica
        self._kind = kind

    def remote(self):
        return (self._kind, self._replica)


class _FakeReplica:
    def __init__(self, name, healthy=True, qlen=0):
        self._actor_id = _AID(name)
        self.healthy = healthy
        self.qlen = qlen

    @property
    def check_health(self):
        return _FakeMethod(self, "health")

    @property
    def get_queue_len(self):
        return _FakeMethod(self, "qlen")


def _fake_get(ref, timeout=None):
    kind, replica = ref
    if not replica.healthy:
        raise RuntimeError(f"replica {replica._actor_id.hex()} is dead")
    return replica.qlen if kind == "qlen" else True


def test_replica_ejection_and_readmission(monkeypatch):
    from ray_tpu.serve import router as router_mod
    monkeypatch.setattr(router_mod.ray_tpu, "get", _fake_get)

    cfg = RouterConfig(ejection_threshold=2, ejection_cooldown_s=0.2,
                       health_probe_timeout_s=0.5)
    rs = ReplicaSet(cfg)
    r1, r2 = _FakeReplica("r1"), _FakeReplica("r2")
    rs.update([r1, r2], 0)

    # below the threshold nothing is ejected; success resets the count
    assert not rs.record_failure(r1)
    rs.record_success(r1)
    assert not rs.record_failure(r1)
    assert rs.record_failure(r1)  # 2 consecutive -> ejected
    assert rs.ejections == 1

    # ejected replica takes no traffic
    r1.healthy = False
    for _ in range(10):
        assert rs.choose() is r2

    # cooldown elapses but the health probe fails: stays out, cooldown re-arms
    time.sleep(0.25)
    assert rs.choose() is r2
    assert rs.readmissions == 0

    # replica recovers: after the next cooldown the probe readmits it
    r1.healthy = True
    time.sleep(0.25)
    chosen = {rs.choose()._actor_id.hex() for _ in range(20)}
    assert "r1" in chosen
    assert rs.readmissions == 1

    # table refresh drops breaker state for replicas no longer routed
    rs.record_failure(r2)
    rs.update([r1], 1)
    assert "r2" not in rs._fails and "r2" not in rs._ejected


def test_router_queue_probe_config_knobs(monkeypatch):
    """The 2.0s probe timeout / 0.5s staleness are config now: a wide
    staleness window serves cached queue lengths without any probe RPC."""
    from ray_tpu.serve import router as router_mod

    def _no_rpc(ref, timeout=None):
        raise AssertionError("probe RPC issued despite fresh cache")

    rs = ReplicaSet(RouterConfig(queue_len_staleness_s=100.0))
    r1, r2 = _FakeReplica("a", qlen=0), _FakeReplica("b", qlen=5)
    rs.update([r1, r2], 0)
    now = time.monotonic()
    # probe cache is keyed by STABLE replica identity (actor id hex), not
    # list index — a table reshuffle must not swap cached queue lengths
    rs._qlen = {"a": (now, 0), "b": (now, 5)}
    monkeypatch.setattr(router_mod.ray_tpu, "get", _no_rpc)
    for _ in range(10):
        assert rs.choose() is r1  # cached lengths decide; no RPC

    # with a zero staleness window every choose re-probes
    rs2 = ReplicaSet(RouterConfig(queue_len_staleness_s=0.0,
                                  queue_probe_timeout_s=0.25))
    rs2.update([r1, r2], 0)
    seen_timeouts = []

    def _probing_get(ref, timeout=None):
        seen_timeouts.append(timeout)
        return _fake_get(ref)

    monkeypatch.setattr(router_mod.ray_tpu, "get", _probing_get)
    assert rs2.choose() is r1
    assert seen_timeouts and all(t == 0.25 for t in seen_timeouts)


def test_worker_killer_max_kills():
    from ray_tpu.util.chaos import WorkerKiller

    class _Proc:
        def __init__(self):
            self.killed = False

        def poll(self):
            return 1 if self.killed else None

        def kill(self):
            self.killed = True

    class _Info:
        def __init__(self):
            self.proc = _Proc()
            self.actor_id = None

    class _Agent:
        def __init__(self, n):
            self._lock = threading.Lock()
            self._workers = {i: _Info() for i in range(n)}

    class _Cluster:
        def __init__(self):
            self.nodes = [_Agent(6)]

    cluster = _Cluster()
    killer = WorkerKiller(cluster, interval_s=0.01, max_kills=2, seed=3)
    killer.start()
    time.sleep(0.5)
    report = killer.stop()
    dead = sum(1 for info in cluster.nodes[0]._workers.values()
               if info.proc.killed)
    assert report["kills"] == 2
    assert dead == 2  # the cap held even though victims remained


def test_batching_respects_deadline():
    from ray_tpu.serve.batching import batch

    async def main():
        @batch(max_batch_size=4, batch_wait_timeout_s=0.01)
        async def double(items):
            return [x * 2 for x in items]

        # expired deadline: refused at admission, no batch slot consumed
        with request_deadline.scope(time.time() - 1.0):
            with pytest.raises(DeadlineExceededError):
                await double(1)

        # live deadline: normal result
        with request_deadline.scope(time.time() + 10.0):
            assert await double(2) == 4

        # the wait for the batch result is bounded by the REMAINING budget
        @batch(max_batch_size=2, batch_wait_timeout_s=0.01)
        async def slow(items):
            await asyncio.sleep(2.0)
            return items

        t0 = time.monotonic()
        with request_deadline.scope(time.time() + 0.25):
            with pytest.raises(DeadlineExceededError):
                await slow(1)
        assert time.monotonic() - t0 < 1.5

    asyncio.run(main())


# ---- cluster tests ---------------------------------------------------------

def test_deadline_rides_task_spec(serve_shutdown):
    """The ambient deadline crosses process hops via TaskSpec.deadline (the
    trace_ctx carrier pattern), including nested submits; expired specs are
    shed before execution."""

    @ray_tpu.remote
    def read_deadline():
        return request_deadline.current()

    @ray_tpu.remote
    def read_deadline_nested():
        # the executor re-establishes the scope, so a child submit inherits
        return ray_tpu.get(read_deadline.remote(), timeout=30)

    assert ray_tpu.get(read_deadline.remote(), timeout=30) is None

    dl = time.time() + 25.0
    with request_deadline.scope(dl):
        direct = read_deadline.remote()
        nested = read_deadline_nested.remote()
    assert ray_tpu.get(direct, timeout=30) == dl
    assert ray_tpu.get(nested, timeout=30) == dl

    @ray_tpu.remote
    class Holder:
        def read(self):
            return request_deadline.current()

    h = Holder.remote()
    with request_deadline.scope(dl):
        ref = h.read.remote()
    assert ray_tpu.get(ref, timeout=30) == dl

    # an expired spec is refused before execution starts
    with request_deadline.scope(time.time() - 0.5):
        shed = read_deadline.remote()
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(shed, timeout=30)
    assert isinstance(ei.value.cause, DeadlineExceededError)


def test_pubsub_handler_registry(serve_shutdown):
    """Worker runtimes expose app-level CP pubsub subscriptions (the hook
    the Serve controller uses for node-death events)."""
    from ray_tpu.core import api

    rt = api._get_runtime()
    got = []
    rt.register_pubsub_handler("robustness_test_chan", got.append)
    rt.cp_client.call(
        "publish", {"channel": "robustness_test_chan",
                    "msg": {"event": "hello"}}, timeout=10.0)
    deadline = time.monotonic() + 10.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.05)
    assert got and got[0]["event"] == "hello"


def test_controller_health_threshold_and_no_leak(serve_shutdown):
    """One transient health-check miss must not cost a replica; at the
    threshold the replica is dropped AND killed (no worker leak)."""

    @serve.deployment(num_replicas=1, health_check_period_s=0.2,
                      health_check_failure_threshold=4)
    class Moody:
        def __init__(self):
            self.uid = uuid.uuid4().hex
            self.fail_next = 0

        def __call__(self, _):
            return self.uid

        def set_fail(self, n):
            self.fail_next = n
            return True

        def check_health(self):
            if self.fail_next > 0:
                self.fail_next -= 1
                raise RuntimeError("transiently sick")

    handle = serve.run(Moody.bind(), name="moody", route_prefix=None)
    uid0 = handle.remote(0).result(timeout_s=30)

    # 2 consecutive failures < threshold 4: the replica survives
    assert handle.set_fail.remote(2).result(timeout_s=30)
    time.sleep(2.0)
    assert handle.remote(0).result(timeout_s=30) == uid0

    # persistent failure: dropped at the threshold and replaced
    handle.set_fail.remote(10_000).result(timeout_s=30)
    deadline = time.time() + 60.0
    uid1 = uid0
    while time.time() < deadline:
        try:
            uid1 = handle.remote(0).result(timeout_s=10)
            if uid1 != uid0:
                break
        except Exception:
            pass
        time.sleep(0.3)
    assert uid1 != uid0, "unhealthy replica was never replaced"

    # no leak: exactly one ServeReplica actor remains ALIVE (the old one
    # was ray_tpu.kill()ed, not abandoned)
    from ray_tpu.util import state as state_api
    deadline = time.time() + 30.0
    alive = None
    while time.time() < deadline:
        alive = [a for a in state_api.list_actors()
                 if "ServeReplica" in str(a.get("class_name", ""))
                 and a.get("state") == "ALIVE"]
        if len(alive) == 1:
            break
        time.sleep(0.3)
    assert len(alive) == 1, f"leaked replica actors: {alive}"
    serve.delete("moody")


def test_router_retry_absorbs_dead_replica(serve_shutdown):
    """A killed replica's in-flight/new calls fail with an actor fault; the
    router retries them on the surviving replica (retry budget) and ejects
    the dead one after consecutive failures."""

    @serve.deployment(num_replicas=2, health_check_period_s=5.0,
                      health_check_failure_threshold=1000)
    def echo(x):
        return x

    serve.run(echo.bind(), name="appretry", route_prefix=None)
    from ray_tpu.serve.controller import get_or_create_controller
    ctl = get_or_create_controller()
    # staleness wide enough that the first post-kill call still sees the
    # warmup probe's idle entry and PICKS the corpse (forcing the retry
    # path), short enough that the fault-poisoned entry later expires and
    # the re-probe's actor fault can finish ejecting it
    router = Router(ctl, "appretry", RouterConfig(
        queue_len_staleness_s=1.0, ejection_threshold=2,
        ejection_cooldown_s=60.0))
    try:
        for i in range(5):  # warm the routing table + qlen cache
            out, _ = router.call("echo", "__call__", (i,), {}, timeout_s=30)
            assert out == i

        table = ray_tpu.get(ctl.get_routing_table.remote("appretry"),
                            timeout=10)
        replicas = table["echo"][0]
        assert len(replicas) == 2
        ray_tpu.kill(replicas[0])
        time.sleep(0.5)  # let the death propagate to submitters

        outs = [router.call("echo", "__call__", (i,), {}, timeout_s=30)[0]
                for i in range(20)]
        assert outs == list(range(20))
        stats = router.stats_snapshot()
        assert stats["requests"] == 25
        assert stats["retries"] >= 1, f"no retry recorded: {stats}"
        # the recorded fault poisoned the corpse's qlen-cache entry, so
        # every call since landed on the survivor FIRST try (ISSUE 14:
        # a failover redispatch must not rediscover the corpse). Once
        # the poison expires, the next selection re-probes it, the
        # probe's actor fault charges the breaker, and it is ejected.
        time.sleep(1.1)
        for i in range(3):
            out, _ = router.call("echo", "__call__", (i,), {}, timeout_s=30)
            assert out == i
        stats = router.stats_snapshot()
        assert stats["ejections"] >= 1, f"dead replica never ejected: {stats}"
    finally:
        router.stop()
    serve.delete("appretry")


def test_proxy_deadline_shed_and_error_shape(serve_shutdown):
    """Expired requests shed with 503 + Retry-After before reaching a
    replica; /v1 routes get the OpenAI-style JSON error envelope; counters
    are served at /-/stats."""

    @serve.deployment
    def echo(payload):
        return {"got": payload}

    serve.run(echo.bind(), name="pxapp", route_prefix="/px")
    serve.run(echo.options(name="v1echo").bind(), name="v1app",
              route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"

    # healthy request (relative timeout header) passes
    req = urllib.request.Request(
        f"{base}/px", data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Timeout-S": "30"})
    body = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert body == {"got": {"a": 1}}

    # expired absolute deadline: fast 503, Retry-After, request never
    # reaches a replica
    req = urllib.request.Request(
        f"{base}/px", data=b"{}",
        headers={"X-Request-Deadline": "1.0"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 503
    assert ei.value.headers.get("Retry-After") == "1"

    # /v1 routes speak the OpenAI error envelope
    req = urllib.request.Request(
        f"{base}/v1/chat/completions", data=b"{}",
        headers={"X-Request-Deadline": "1.0"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 503
    err = json.loads(ei.value.read())
    assert err["error"]["type"] == "timeout"
    assert err["error"]["code"] == 503
    assert "deadline" in err["error"]["message"]

    stats = json.loads(urllib.request.urlopen(
        f"{base}/-/stats", timeout=10).read())
    assert stats["shed_expired"] >= 2
    assert stats["ok"] >= 1
    assert "routers" in stats
    serve.delete("pxapp")
    serve.delete("v1app")


def test_proxy_enforces_request_timeout(serve_shutdown):
    """A slow replica call is cut off at the deployment's request_timeout_s
    (bounded get + 503), not at a hardcoded 120s."""

    @serve.deployment(request_timeout_s=1.0)
    def sleepy(payload):
        time.sleep(5.0)
        return {"ok": True}

    serve.run(sleepy.bind(), name="slowapp", route_prefix="/slow")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"

    t0 = time.monotonic()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            urllib.request.Request(f"{base}/slow", data=b"{}"), timeout=30)
    elapsed = time.monotonic() - t0
    assert ei.value.code == 503
    assert ei.value.headers.get("Retry-After") == "1"
    assert elapsed < 4.0, f"deadline not enforced: took {elapsed:.1f}s"

    stats = json.loads(urllib.request.urlopen(
        f"{base}/-/stats", timeout=10).read())
    assert stats["deadline_exceeded"] >= 1
    serve.delete("slowapp")


def test_proxy_overload_shed(serve_shutdown):
    """max_inflight admission control sheds with 503 + Retry-After."""
    from ray_tpu.serve.controller import get_or_create_controller
    from ray_tpu.serve.proxy import HTTPProxy

    @serve.deployment
    def echo(payload):
        return {"got": payload}

    serve.run(echo.bind(), name="ovapp", route_prefix="/ov")
    proxy = HTTPProxy(get_or_create_controller(), port=0, max_inflight=0)
    proxy.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{proxy.port}/ov", data=b"{}"),
                timeout=30)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") == "1"
        assert proxy.stats["shed_overload"] == 1
    finally:
        proxy.stop()
    serve.delete("ovapp")


# ---- chaos: serve keeps its SLO while a replica-bearing node dies ---------
# LAST in the file on purpose: it tears down the module-shared runtime and
# builds its own multi-node cluster.

@pytest.mark.slow
def test_serve_survives_node_death_under_traffic():
    """Acceptance: with a NodeKiller killing one replica-bearing node under
    sustained proxy traffic, >= 99% of requests succeed (retries + ejection
    absorb the death), no successful response exceeds its deadline plus
    grace, and already-expired requests are shed with 503 (shed counters)."""
    import concurrent.futures

    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config
    from ray_tpu.util.chaos import NodeKiller

    serve.shutdown()
    ray_tpu.shutdown()
    # the in-process CP reads the live Config singleton: tighten node-death
    # detection BEFORE the cluster starts
    cfg = get_config()
    cfg.health_check_period_s = 0.2
    cfg.health_check_failure_threshold = 3

    cluster = Cluster()
    cluster.add_node(num_cpus=1)   # node0: spared by NodeKiller; controller
    ray_tpu.init(address=cluster.address, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })
    try:
        # pin the controller to node0 by creating it while node0 is the
        # only node, THEN add the replica-bearing nodes
        from ray_tpu.serve.controller import get_or_create_controller
        ctl = get_or_create_controller()
        ray_tpu.get(ctl.status.remote(), timeout=60)
        cluster.add_node(num_cpus=3)
        cluster.add_node(num_cpus=3)

        REQUEST_TIMEOUT_S = 15.0
        GRACE_S = 3.0

        @serve.deployment(num_replicas=3, health_check_period_s=0.2,
                          health_check_failure_threshold=3,
                          request_timeout_s=REQUEST_TIMEOUT_S)
        def work(payload):
            time.sleep(0.02)
            return {"ok": True}

        serve.run(work.bind(), name="chaosapp", route_prefix="/chaos")
        proxy = serve.start_http_proxy(port=0)
        base = f"http://127.0.0.1:{proxy.port}"

        results = []  # (ok: bool, elapsed: float, detail: str)
        results_lock = threading.Lock()
        stop_traffic = threading.Event()
        traffic_t0 = time.monotonic()

        def one_request():
            t0 = time.monotonic()
            try:
                resp = urllib.request.urlopen(
                    urllib.request.Request(f"{base}/chaos", data=b"{}"),
                    timeout=REQUEST_TIMEOUT_S + GRACE_S)
                ok = resp.status == 200 and \
                    json.loads(resp.read())["ok"] is True
                detail = f"http {resp.status}"
            except urllib.error.HTTPError as e:
                ok = False
                detail = f"http {e.code}: {e.read()[:200]!r}"
            except Exception as e:  # noqa: BLE001 — failure is data here
                ok = False
                detail = repr(e)[:200]
            with results_lock:
                results.append(
                    (ok, time.monotonic() - t0,
                     f"@{t0 - traffic_t0:.1f}s {detail}"))

        def traffic(worker_id):
            while not stop_traffic.is_set():
                one_request()
                time.sleep(0.02)

        killer = NodeKiller(cluster, interval_s=3.0, max_kills=1, seed=7)
        with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
            futs = [pool.submit(traffic, i) for i in range(4)]
            killer.start()
            time.sleep(18.0)  # kill lands ~3s in; recovery under load
            stop_traffic.set()
            for f in futs:
                f.result(timeout=REQUEST_TIMEOUT_S + GRACE_S + 10)
        report = killer.stop()
        assert report["nodes_killed"] == 1, "chaos never fired"

        total = len(results)
        succ = sum(1 for ok, _, _ in results if ok)
        assert total >= 100, f"not enough traffic generated: {total}"
        rate = succ / total
        failures = [(f"{t:.1f}s", d) for ok, t, d in results if not ok]
        if rate < 0.99:
            try:
                dbg = urllib.request.urlopen(
                    f"{base}/-/stats", timeout=10).read().decode()
            except Exception as e:  # noqa: BLE001
                dbg = repr(e)
            raise AssertionError(
                f"success rate {rate:.3f} ({succ}/{total}) under node "
                f"death; failures: {failures[:10]}; server stats: {dbg}")
        # no successful response may exceed its deadline plus grace
        slow = [t for ok, t, _ in results
                if ok and t > REQUEST_TIMEOUT_S + GRACE_S]
        assert not slow, f"successful responses exceeded deadline+grace: {slow}"

        # already-expired requests are shed with 503 before any replica
        for _ in range(3):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    urllib.request.Request(
                        f"{base}/chaos", data=b"{}",
                        headers={"X-Request-Deadline": "1.0"}), timeout=30)
            assert ei.value.code == 503
        stats = json.loads(urllib.request.urlopen(
            f"{base}/-/stats", timeout=10).read())
        assert stats["shed_expired"] >= 3
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_controller_replaces_replicas_after_cp_restart():
    """Pubsub resubscription regression: subscriptions live only in CP
    memory, so after a CP restart every subscriber must re-issue them on
    the new epoch and reconcile missed death events. Restart the CP, THEN
    kill a replica-bearing node: the serve controller must still hear
    about the death and replace the lost replicas — before the fix it
    silently never received another node event."""
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config

    serve.shutdown()
    ray_tpu.shutdown()
    cfg = get_config()
    cfg.health_check_period_s = 0.2
    cfg.health_check_failure_threshold = 3

    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # node0: controller home
    ray_tpu.init(address=cluster.address, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })
    try:
        from ray_tpu.serve.controller import get_or_create_controller
        ctl = get_or_create_controller()
        ray_tpu.get(ctl.status.remote(), timeout=60)
        victim = cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)

        @serve.deployment(num_replicas=2, health_check_period_s=0.2,
                          health_check_failure_threshold=3)
        def echo(payload):
            return {"ok": True}

        serve.run(echo.bind(), name="resub", route_prefix="/resub")
        proxy = serve.start_http_proxy(port=0)
        base = f"http://127.0.0.1:{proxy.port}"
        assert urllib.request.urlopen(
            urllib.request.Request(f"{base}/resub", data=b"{}"),
            timeout=30).status == 200

        # ---- CP restart: the controller's subscription dies with it ----
        addr = cluster.kill_control_plane()
        time.sleep(0.5)
        cluster.restart_control_plane(addr)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                if sum(1 for n in ray_tpu.nodes() if n["alive"]) >= 3:
                    break
            except Exception:  # noqa: BLE001 — CP client reconnecting
                pass
            time.sleep(0.2)
        else:
            raise AssertionError("agents never re-registered after restart")
        # let subscribers finish their epoch-change resubscription
        time.sleep(1.0)

        # ---- now kill a replica-bearing node ----
        cluster.remove_node(victim, graceful=False)

        deadline = time.monotonic() + 60.0
        last = None
        while time.monotonic() < deadline:
            last = ray_tpu.get(ctl.status.remote(), timeout=30)
            dep = last.get("resub#echo") or {}
            if dep.get("replicas") == 2 and not dep.get("draining"):
                break
            time.sleep(0.5)
        else:
            raise AssertionError(
                f"controller never replaced replicas lost with the node "
                f"after a CP restart (resubscription broken?): {last}")

        # the replacement replicas actually serve
        assert urllib.request.urlopen(
            urllib.request.Request(f"{base}/resub", data=b"{}"),
            timeout=30).status == 200
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_faultschedule_multifault_serve_slo():
    """Deterministic multi-fault chaos: ONE seeded FaultSchedule stacks an
    RPC slowdown, a worker kill, a graceful drain, and a CP restart under
    sustained proxy traffic; >= 99% of requests succeed, every event fires,
    and no successful response exceeds deadline+grace."""
    import concurrent.futures

    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config
    from ray_tpu.util.chaos import FaultSchedule

    serve.shutdown()
    ray_tpu.shutdown()
    cfg = get_config()
    cfg.health_check_period_s = 0.2
    cfg.health_check_failure_threshold = 3

    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # node0: controller home, never a victim
    ray_tpu.init(address=cluster.address, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })
    try:
        from ray_tpu.serve.controller import get_or_create_controller
        ctl = get_or_create_controller()
        ray_tpu.get(ctl.status.remote(), timeout=60)
        cluster.add_node(num_cpus=3)
        cluster.add_node(num_cpus=3)

        REQUEST_TIMEOUT_S = 15.0
        GRACE_S = 3.0

        @serve.deployment(num_replicas=2, health_check_period_s=0.2,
                          health_check_failure_threshold=3,
                          request_timeout_s=REQUEST_TIMEOUT_S)
        def work(payload):
            time.sleep(0.02)
            return {"ok": True}

        serve.run(work.bind(), name="mfapp", route_prefix="/mf")
        proxy = serve.start_http_proxy(port=0)
        base = f"http://127.0.0.1:{proxy.port}"

        results = []
        results_lock = threading.Lock()
        stop_traffic = threading.Event()

        def traffic():
            while not stop_traffic.is_set():
                t0 = time.monotonic()
                try:
                    resp = urllib.request.urlopen(
                        urllib.request.Request(f"{base}/mf", data=b"{}"),
                        timeout=REQUEST_TIMEOUT_S + GRACE_S)
                    ok = resp.status == 200 and \
                        json.loads(resp.read())["ok"] is True
                    detail = f"http {resp.status}"
                except Exception as e:  # noqa: BLE001 — failure is data
                    ok, detail = False, repr(e)[:200]
                with results_lock:
                    results.append((ok, time.monotonic() - t0, detail))
                time.sleep(0.02)

        sched = FaultSchedule(cluster, [
            (1.0, "rpc_delay", {"spec": "*:0:0:0.02", "duration_s": 2.0}),
            (2.0, "worker_kill", {"spare_actors": False}),
            (4.0, "node_drain", {"wait": True}),
            (9.0, "cp_restart", {"down_s": 1.0}),
        ], seed=11)
        with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
            futs = [pool.submit(traffic) for _ in range(4)]
            sched.start()
            time.sleep(16.0)
            stop_traffic.set()
            for f in futs:
                f.result(timeout=REQUEST_TIMEOUT_S + GRACE_S + 10)
        report = sched.stop()

        assert len(report) == 4 and all(e["ok"] for e in report), report
        total = len(results)
        succ = sum(1 for ok, _, _ in results if ok)
        assert total >= 100, f"not enough traffic generated: {total}"
        rate = succ / total
        failures = [d for ok, _, d in results if not ok]
        assert rate >= 0.99, (
            f"success rate {rate:.3f} ({succ}/{total}) under the "
            f"multi-fault schedule; failures: {failures[:10]}; "
            f"events: {report}")
        slow = [t for ok, t, _ in results
                if ok and t > REQUEST_TIMEOUT_S + GRACE_S]
        assert not slow, f"successful responses exceeded deadline+grace: {slow}"
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        cluster.shutdown()
