"""Actor API: ActorClass / ActorHandle / ActorMethod.

TPU-native analog of the reference's actor surface
(/root/reference/python/ray/actor.py — ActorClass:1181, _remote:1492,
ActorHandle:1851, _actor_method_call:2047, ActorMethod._remote:792).
"""

from __future__ import annotations

import functools
from typing import Any

from ray_tpu.core.ids import ActorID
from ray_tpu.core.remote_function import _build_resources, _build_strategy


def method(*args, **options):
    """Method decorator (ref: ray.method — actor.py:792): annotate per-method
    defaults. Supported: concurrency_group. For num_returns use
    `.options(num_returns=N)` at the call site — handles here are plain data
    (reconstructible from an actor id alone) and never see the class body,
    so a method-level default could not be honored."""
    def decorate(fn):
        unknown = set(options) - {"concurrency_group"}
        if unknown:
            raise ValueError(
                f"unsupported @method option(s) {sorted(unknown)}; use "
                f".options(...) at the call site")
        if "concurrency_group" in options:
            fn._concurrency_group = options["concurrency_group"]
        return fn

    if len(args) == 1 and callable(args[0]) and not options:
        return args[0]
    return decorate


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1,
                 max_task_retries: int | None = None,
                 concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._max_task_retries = max_task_retries
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs)

    def bind(self, *args):
        """Bind this method into a compiled DAG (ref: dag node binding on
        actor methods, python/ray/dag/__init__.py): args may mix
        constants, InputNode, and other DAG nodes."""
        from ray_tpu.dag.compiled import DAGNode
        return DAGNode(self._handle, self._method_name, args)

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            num_returns=opts.get("num_returns", self._num_returns),
            max_task_retries=opts.get("max_task_retries", self._max_task_retries),
            concurrency_group=opts.get("concurrency_group",
                                       self._concurrency_group))

    def _remote(self, args, kwargs):
        from ray_tpu.core import api
        rt = api._get_runtime()
        h = self._handle
        retries = self._max_task_retries
        if retries is None:
            retries = h._max_task_retries
        refs = rt.submit_actor_task(
            h._actor_id, self._method_name, args, kwargs,
            num_returns=self._num_returns, max_task_retries=retries,
            name=f"{h._class_name}.{self._method_name}",
            concurrency_group=self._concurrency_group)
        if self._num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        if self._num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use '.{self._method_name}.remote()'.")


class ActorHandle:
    """Serializable handle to a live actor (ref: actor.py:1851). Handles are
    plain data — any process holding one can submit ordered method calls."""

    def __init__(self, actor_id: ActorID, class_name: str, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_") and name != "__rtpu_call__":
            # __rtpu_call__ is the generic run-a-callable-on-the-actor
            # entry (reference: actor.__ray_call__)
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name, self._max_task_retries))

    def kill(self, no_restart: bool = True):
        from ray_tpu.core import api
        api.kill(self, no_restart=no_restart)


class ActorClass:
    def __init__(self, cls: type, **options):
        self._cls = cls
        self._options = options
        functools.update_wrapper(self, cls, updated=[])

    def options(self, **options) -> "ActorClass":
        return ActorClass(self._cls, **{**self._options, **options})

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, options) -> ActorHandle:
        from ray_tpu.core import api
        rt = api._get_runtime()
        actor_id = ActorID.of(rt.job_id)
        resources = _build_resources(options)
        if options.get("num_cpus") is None and "CPU" not in (options.get("resources") or {}):
            # actors default to 0 CPU when running, 1 for placement in the
            # reference; we reserve 1 CPU unless told otherwise
            resources.setdefault("CPU", 1.0)
        is_async = _has_async_methods(self._cls)
        rt.submit_actor_creation(
            self._cls, args, kwargs, actor_id=actor_id,
            resources=resources,
            name=options.get("name", ""),
            detached=options.get("lifetime") == "detached",
            max_restarts=int(options.get("max_restarts", 0)),
            max_task_retries=int(options.get("max_task_retries", 0)),
            max_concurrency=int(options.get("max_concurrency", 1000 if is_async else 1)),
            is_async=is_async,
            strategy=_build_strategy(options),
            runtime_env=options.get("runtime_env"),
            concurrency_groups=options.get("concurrency_groups"))
        handle = ActorHandle(actor_id, self._cls.__name__,
                             max_task_retries=int(options.get("max_task_retries", 0)))
        return handle

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use '{self._cls.__name__}.remote()'.")


def _has_async_methods(cls: type) -> bool:
    import inspect
    return any(inspect.iscoroutinefunction(m)
               for _, m in inspect.getmembers(cls, predicate=inspect.isfunction))
