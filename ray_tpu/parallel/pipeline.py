"""Pipeline parallelism: GPipe-style microbatched execution over a mesh axis.

The reference has no in-tree training pipeline parallelism — it delegates to
vLLM's pipeline_parallel_size for serving (SURVEY.md §2.3 row PP). Here PP is
native: layers are grouped into S stages whose parameters live on the
"pipeline" mesh axis; activations flow stage→stage with `lax.ppermute` inside a
`shard_map`, and jax autodiff differentiates straight through the permute (the
backward pass is the reverse ring) — no hand-written send/recv schedule.

Schedule: GPipe with M microbatches over S stages, M + S - 1 ticks. Bubble
fraction (S-1)/(M+S-1) — pick M >= 4·S. The stage loop is a `lax.fori_loop`,
so the program is O(1) in compiled size regardless of M.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import shard_map_compat as shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(init_fn: Callable, n_stages: int, rng, *args):
    """Init per-stage params with a leading stage dim: vmap over stage index.
    ``init_fn(rng, stage_idx, *args) -> params`` pytree."""
    rngs = jax.random.split(rng, n_stages)
    return jax.vmap(lambda r, i: init_fn(r, i, *args))(rngs, jnp.arange(n_stages))


def pipeline_apply(stage_fn: Callable, stage_params, x, mesh: Mesh, *,
                   axis_name: str = "pipeline", num_microbatches: int | None = None):
    """Run ``x`` through S pipeline stages.

    stage_fn(params_slice, microbatch) -> microbatch (same shape/dtype)
    stage_params: pytree with leading dim S, sharded P(axis_name, ...)
    x: [batch, ...] — batch is split into M microbatches.
    """
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        one = jax.tree.map(lambda p: p[0], stage_params)
        return stage_fn(one, x)
    n_stages = mesh.shape[axis_name]
    m = num_microbatches or (4 * n_stages)
    batch = x.shape[0]
    if batch % m != 0:
        raise ValueError(f"batch {batch} not divisible by {m} microbatches")
    mb = batch // m
    xs = x.reshape(m, mb, *x.shape[1:])

    def sharded(params, xs):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        stage = jax.lax.axis_index(axis_name)
        # send each stage's output to the next; the wrap-around edge carries
        # garbage that stage 0 ignores (it reads fresh microbatches)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        out_buf = jnp.zeros_like(xs)
        state = jnp.zeros_like(xs[0])

        def tick(t, carry):
            state, out_buf = carry
            mb_in = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, m - 1), axis=0, keepdims=False)
            inp = jnp.where(stage == 0, mb_in, state)
            out = stage_fn(params, inp)
            # last stage writes microbatch t-(S-1) when valid
            write_idx = t - (n_stages - 1)
            do_write = (stage == n_stages - 1) & (write_idx >= 0)
            out_buf = jax.lax.cond(
                do_write,
                lambda b: jax.lax.dynamic_update_index_in_dim(
                    b, out, jnp.maximum(write_idx, 0), axis=0),
                lambda b: b, out_buf)
            state = jax.lax.ppermute(out, axis_name, perm)
            return state, out_buf

        _, out_buf = jax.lax.fori_loop(0, m + n_stages - 1, tick, (state, out_buf))
        # only the last stage holds real outputs; broadcast over the axis
        out_buf = jnp.where(stage == n_stages - 1, out_buf, 0.0)
        return jax.lax.psum(out_buf, axis_name)

    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    out = shard_map(
        sharded, mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
        check=False)(stage_params, xs)
    return out.reshape(batch, *x.shape[1:])
