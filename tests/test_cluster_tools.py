"""Jobs, CLI, runtime envs, dashboard (reference: dashboard/modules/job/,
scripts/scripts.py, _private/runtime_env/, dashboard/)."""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

import ray_tpu


def test_job_submission(ray_start_regular, tmp_path):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    script = tmp_path / "job.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()\n"
        "@ray_tpu.remote\n"
        "def f(x): return x + 1\n"
        "print('total:', sum(ray_tpu.get([f.remote(i) for i in range(4)])))\n"
        "ray_tpu.shutdown()\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    status = client.wait_until_finished(job_id, timeout=120.0)
    assert status == JobStatus.SUCCEEDED
    assert "total: 10" in client.get_job_logs(job_id)
    assert any(j["job_id"] == job_id for j in client.list_jobs())


def test_job_failure_recorded(ray_start_regular, tmp_path):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    status = client.wait_until_finished(job_id, timeout=60.0)
    assert status == JobStatus.FAILED
    assert client.get_job_info(job_id)["return_code"] == 3


def test_runtime_env_env_vars_and_working_dir(ray_start_regular, tmp_path):
    d = tmp_path / "wd"
    d.mkdir()
    (d / "mymod_rt.py").write_text("VALUE = 123")
    (d / "data.txt").write_text("hello-env")

    @ray_tpu.remote(runtime_env={"env_vars": {"RT_FLAG": "yes"},
                                 "working_dir": str(d)})
    def probe():
        import mymod_rt
        return (os.environ["RT_FLAG"], mymod_rt.VALUE,
                open("data.txt").read())

    assert ray_tpu.get(probe.remote(), timeout=90) == (
        "yes", 123, "hello-env")

    # plain tasks keep the clean environment
    @ray_tpu.remote
    def clean():
        return os.environ.get("RT_FLAG")

    assert ray_tpu.get(clean.remote(), timeout=90) is None


def test_log_to_driver(ray_start_regular, capfd):
    """Worker stdout streams to the driver with a provenance prefix
    (ref: _private/log_monitor.py -> worker.py print_to_stdstream)."""

    @ray_tpu.remote
    def noisy():
        print("log-stream-probe-xyzzy")
        return 1

    assert ray_tpu.get(noisy.remote(), timeout=60) == 1
    deadline = time.time() + 5.0
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().out
        if "log-stream-probe-xyzzy" in seen:
            break
        time.sleep(0.2)
    assert "log-stream-probe-xyzzy" in seen
    assert "(pid=" in seen


def test_log_streaming_survives_dropped_pushes(tmp_path, capfd):
    """Pub/sub is at-least-once: with EVERY push delivery chaos-dropped
    (rpc fault injection), the subscriber's long-poll recovery loop still
    delivers — seq-dedup'd (ref: pubsub long-poll, pubsub.proto:224)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, _system_config={
        "testing_rpc_failure": "pubsub:1.0:0",  # drop all pubsub pushes
    })
    try:
        @ray_tpu.remote
        def noisy():
            print("poll-recovery-probe-plugh")
            return 1

        assert ray_tpu.get(noisy.remote(), timeout=60) == 1
        deadline = time.time() + 20.0
        seen = ""
        while time.time() < deadline:
            seen += capfd.readouterr().out
            if "poll-recovery-probe-plugh" in seen:
                break
            time.sleep(0.3)
        assert "poll-recovery-probe-plugh" in seen
    finally:
        ray_tpu.shutdown()


def _make_wheel(tmp_path, version: str) -> str:
    """Build a minimal pure-python wheel (a wheel is just a zip) so pip
    runtime_env tests install fully offline."""
    import zipfile

    path = tmp_path / f"rtpu_testpkg-{version}-py3-none-any.whl"
    di = f"rtpu_testpkg-{version}.dist-info"
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("rtpu_testpkg/__init__.py",
                    f'__version__ = "{version}"\n')
        zf.writestr(f"{di}/METADATA",
                    "Metadata-Version: 2.1\nName: rtpu-testpkg\n"
                    f"Version: {version}\n")
        zf.writestr(f"{di}/WHEEL",
                    "Wheel-Version: 1.0\nGenerator: test\n"
                    "Root-Is-Purelib: true\nTag: py3-none-any\n")
        zf.writestr(f"{di}/RECORD", "")
    return str(path)


def test_pip_runtime_env_conflicting_versions(ray_start_regular, tmp_path):
    """Two jobs' tasks run against CONFLICTING package versions on one
    cluster: each pip runtime_env materializes its own virtualenv (uv when
    available, stdlib venv otherwise) and the worker pool is keyed per env
    (reference: _private/runtime_env/uv.py, pip.py, uri_cache.py)."""
    whl1 = _make_wheel(tmp_path, "1.0")
    whl2 = _make_wheel(tmp_path, "2.0")

    @ray_tpu.remote
    def ver():
        import rtpu_testpkg
        return rtpu_testpkg.__version__

    r1 = ver.options(runtime_env={"pip": [whl1]}).remote()
    r2 = ver.options(runtime_env={"pip": [whl2]}).remote()
    # generous timeout: each env creates a venv (~10s on a 1-core box)
    assert sorted(ray_tpu.get([r1, r2], timeout=300)) == ["1.0", "2.0"]
    # the base environment must NOT see the package (isolation)
    @ray_tpu.remote
    def base_has():
        try:
            import rtpu_testpkg  # noqa: F401
            return True
        except ImportError:
            return False

    assert ray_tpu.get(base_has.remote(), timeout=60) is False


def test_runtime_env_validation(ray_start_regular):
    from ray_tpu.runtime_env import RuntimeEnvError

    @ray_tpu.remote(runtime_env={"bogus_key": 1})
    def f():
        return 1

    with pytest.raises(RuntimeEnvError):
        f.remote()


def test_dashboard_endpoints(ray_start_regular):
    from ray_tpu.dashboard import start_dashboard

    db = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{db.port}"
        nodes = json.loads(urllib.request.urlopen(
            base + "/api/nodes", timeout=30).read())
        assert nodes and nodes[0]["alive"]
        html = urllib.request.urlopen(base + "/", timeout=30).read().decode()
        assert "ray_tpu dashboard" in html

        # system metrics: run a task so counters move, give the agent one
        # heartbeat to ship node gauges, then scrape
        @ray_tpu.remote
        def probe_task():
            return 1

        assert ray_tpu.get(probe_task.remote(), timeout=60) == 1
        time.sleep(1.5)
        text = urllib.request.urlopen(
            base + "/metrics", timeout=30).read().decode()
        assert "ray_tpu_nodes_alive 1" in text
        assert "ray_tpu_node_workers_total" in text
        assert "ray_tpu_node_resource_total" in text
        # owner-side task latency histogram (VERDICT r2 #10)
        assert "ray_tpu_task_latency_seconds_bucket" in text
        assert 'type="NORMAL"' in text

        # on-demand whole-cluster stack snapshot: driver + agent + the
        # worker that just ran probe_task, with real frames
        stacks = json.loads(urllib.request.urlopen(
            base + "/api/stacks", timeout=60).read())
        names = {s["process"] for s in stacks}
        assert "driver" in names
        assert any("/agent" in n for n in names)
        assert any("/worker-" in n for n in names)
        worker_dump = next(s["stacks"] for s in stacks
                           if "/worker-" in s["process"])
        assert "thread" in worker_dump and "worker.py" in worker_dump
    finally:
        db.stop()


def test_cli_status_and_head(tmp_path):
    ray_tpu.shutdown()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    port = 6399
    subprocess.run(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--port", str(port), "--num-cpus", "2", "--dashboard-port", "-1"],
        check=True, env=env, timeout=120, cwd="/root/repo")
    try:
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu", "status",
             "--address", f"127.0.0.1:{port}"],
            capture_output=True, text=True, env=env, timeout=120,
            cwd="/root/repo")
        assert out.returncode == 0, out.stderr[-500:]
        assert "nodes: 1" in out.stdout
    finally:
        subprocess.run([sys.executable, "-m", "ray_tpu", "stop"],
                       env=env, timeout=60, cwd="/root/repo")


def test_env_cache_gc_lru(tmp_path, monkeypatch):
    """LRU eviction over the cached-env root (reference uri_cache.py):
    oldest entries beyond the budget go; recently-used entries survive
    even when over budget (a live worker may hold them)."""
    import os
    import time

    from ray_tpu.core import config as cfgmod
    from ray_tpu.runtime_env.packaging import gc_env_cache

    root = str(tmp_path / "envs")
    os.makedirs(root)
    # 5 entries, oldest first; entry 4 has no .ready marker (dir mtime)
    for i in range(5):
        d = os.path.join(root, f"venv-{i:02d}")
        os.makedirs(d)
        if i != 4:
            open(os.path.join(d, ".ready"), "w").close()
        age = (10 - i) * 1000  # older for smaller i
        ts = time.time() - age
        os.utime(os.path.join(d, ".ready") if i != 4 else d, (ts, ts))

    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE_MAX_ENVS", "2")
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE_MIN_AGE_S", "600")
    cfgmod.reset_config()
    try:
        evicted = gc_env_cache(root)
        left = sorted(os.listdir(root))
        # budget 2: the 3 oldest evicted
        assert len(evicted) == 3
        assert left == ["venv-03", "venv-04"]
        # min-age shield: make everything recent, over budget -> no eviction
        now = time.time()
        for name in left:
            d = os.path.join(root, name)
            clock = os.path.join(d, ".ready")
            os.utime(clock if os.path.exists(clock) else d, (now, now))
        monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE_MAX_ENVS", "1")
        cfgmod.reset_config()
        assert gc_env_cache(root) == []
        assert sorted(os.listdir(root)) == left
    finally:
        monkeypatch.delenv("RAY_TPU_RUNTIME_ENV_CACHE_MAX_ENVS")
        monkeypatch.delenv("RAY_TPU_RUNTIME_ENV_CACHE_MIN_AGE_S")
        cfgmod.reset_config()


def test_conda_prefix_runtime_env_e2e(ray_start_regular, tmp_path):
    """Second isolation plugin (reference conda.py): an existing env
    prefix runs the worker under THAT interpreter — verified end to end by
    a task reporting its sys.prefix and CONDA_PREFIX."""
    import subprocess
    import sys

    prefix = str(tmp_path / "condaenv")
    subprocess.run([sys.executable, "-m", "venv",
                    "--system-site-packages", prefix],
                   check=True, capture_output=True, timeout=300)
    # the framework must be importable inside the env (same mechanism as
    # the pip plugin's parent-site .pth)
    import glob as _glob
    parent_sites = [p for p in sys.path
                    if p.rstrip("/").endswith("site-packages")]
    for sp in _glob.glob(os.path.join(prefix, "lib", "python*",
                                      "site-packages")):
        with open(os.path.join(sp, "_rtpu_parent_sites.pth"), "w") as f:
            f.write("\n".join(parent_sites + [os.getcwd()]) + "\n")

    @ray_tpu.remote(runtime_env={"conda": {"prefix": prefix}})
    def where():
        import os as _os
        import sys as _sys
        return _sys.prefix, _os.environ.get("CONDA_PREFIX")

    sys_prefix, conda_prefix = ray_tpu.get(where.remote(), timeout=120)
    assert sys_prefix == prefix
    assert conda_prefix == prefix


def test_container_runtime_env_gates():
    """image_uri requires a container runtime ON THE EXECUTING NODE; this
    image has none, so agent-side materialization must fail with a clear
    error (a docker-ful node would instead get the podman/docker argv
    prefix the worker command is wrapped with)."""
    import shutil as _shutil

    from ray_tpu.runtime_env.packaging import (
        RuntimeEnvError, _container_command, materialize_runtime_env)

    if _shutil.which("docker") or _shutil.which("podman"):
        cmd = _container_command({"image_uri": "ubuntu:22.04"})
        assert cmd[-1] == "ubuntu:22.04"
        return
    with pytest.raises(RuntimeEnvError, match="docker or podman"):
        materialize_runtime_env(None, {"image_uri": "ubuntu:22.04"})


def test_env_cache_gc_respects_pins(tmp_path, monkeypatch):
    """Pinned env paths (a live worker runs out of them) survive LRU
    eviction no matter how old; unpinning the owner makes them evictable
    again. Guards against gc rmtree-ing a running worker's venv."""
    import os
    import time

    from ray_tpu.core import config as cfgmod
    from ray_tpu.runtime_env.packaging import (gc_env_cache, pin_env_paths,
                                               unpin_env_paths)

    root = str(tmp_path / "envs")
    os.makedirs(root)
    paths = []
    for i in range(4):
        d = os.path.join(root, f"venv-{i:02d}")
        os.makedirs(d)
        open(os.path.join(d, ".ready"), "w").close()
        ts = time.time() - (10 - i) * 1000  # all well past min age
        os.utime(os.path.join(d, ".ready"), (ts, ts))
        paths.append(d)

    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE_MAX_ENVS", "1")
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE_MIN_AGE_S", "1")
    cfgmod.reset_config()
    try:
        # two workers pin the two OLDEST envs (prime eviction candidates)
        pin_env_paths("worker-a", [paths[0]])
        pin_env_paths("worker-b", [paths[1]])
        evicted = gc_env_cache(root)
        left = sorted(os.listdir(root))
        # budget 1, 3 over: only the unpinned old entry goes; eviction
        # skips pins rather than stopping at them (venv-02 still evicted)
        assert [os.path.basename(p) for p in evicted] == ["venv-02"]
        assert left == ["venv-00", "venv-01", "venv-03"]

        # worker-a dies -> its pin lifts; worker-b's env still survives
        unpin_env_paths("worker-a")
        evicted = gc_env_cache(root)
        assert [os.path.basename(p) for p in evicted] == ["venv-00"]
        assert sorted(os.listdir(root)) == ["venv-01", "venv-03"]

        # unpinning an unknown owner is a harmless no-op
        unpin_env_paths("never-registered")
    finally:
        unpin_env_paths("worker-b")
        monkeypatch.delenv("RAY_TPU_RUNTIME_ENV_CACHE_MAX_ENVS")
        monkeypatch.delenv("RAY_TPU_RUNTIME_ENV_CACHE_MIN_AGE_S")
        cfgmod.reset_config()
