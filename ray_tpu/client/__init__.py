"""Remote-driver client mode (`ray_tpu.init("ray_tpu://host:port")`).

TPU-native analog of the reference's Ray Client (util/client/): a
ClientServer beside the cluster head hosts one real driver per connected
client; the client proxies the runtime API over the framework RPC layer.
"""

from ray_tpu.client.client import ClientRuntime
from ray_tpu.client.server import ClientServer

__all__ = ["ClientRuntime", "ClientServer"]
