"""ray_tpu.data tests (models the reference's data test strategy:
python/ray/data/tests/ — transforms, shuffles, readers, iteration)."""

import json
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rtd


@pytest.fixture(scope="module")
def rt(ray_start_module):
    yield ray_start_module


def test_range_count_schema(rt):
    ds = rtd.range(100)
    assert ds.count() == 100
    assert ds.columns() == ["id"]


def test_take_and_rows(rt):
    rows = rtd.range(10).take(3)
    assert rows == [{"id": 0}, {"id": 1}, {"id": 2}]


def test_map_batches_tasks(rt):
    ds = rtd.range(100, parallelism=4).map_batches(
        lambda b: {"x": b["id"] * 2})
    out = ds.take_all()
    assert sorted(r["x"] for r in out) == list(range(0, 200, 2))


def test_map_batches_fusion(rt):
    ds = rtd.range(10).map_batches(lambda b: {"x": b["id"] + 1}) \
        .map_batches(lambda b: {"x": b["x"] * 10})
    assert "Fused" in ds.stats()
    assert sorted(r["x"] for r in ds.take_all()) == list(range(10, 110, 10))


def test_map_and_filter_and_flat_map(rt):
    ds = rtd.range(20).filter(lambda r: r["id"] % 2 == 0) \
        .map(lambda r: {"v": r["id"] * 10})
    vals = sorted(r["v"] for r in ds.take_all())
    assert vals == [i * 10 for i in range(0, 20, 2)]

    ds2 = rtd.from_items([1, 2]).flat_map(
        lambda r: [{"v": r["item"]}, {"v": r["item"] * 100}])
    assert sorted(x["v"] for x in ds2.take_all()) == [1, 2, 100, 200]


def test_map_batches_actor_compute(rt):
    class AddState:
        def __init__(self):
            self.offset = 1000

        def __call__(self, batch):
            return {"x": batch["id"] + self.offset}

    ds = rtd.range(20, parallelism=2).map_batches(AddState, concurrency=2)
    assert sorted(r["x"] for r in ds.take_all()) == list(range(1000, 1020))


def test_limit_streaming(rt):
    ds = rtd.range(1000, parallelism=10).limit(7)
    assert [r["id"] for r in ds.take_all()] == list(range(7))


def test_iter_batches_exact_sizes(rt):
    sizes = [len(b["id"]) for b in rtd.range(100, parallelism=3)
             .iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in rtd.range(100, parallelism=3)
             .iter_batches(batch_size=32, drop_last=True)]
    assert sizes == [32, 32, 32]


def test_iter_batches_formats(rt):
    b = next(iter(rtd.range(10).iter_batches(batch_size=5,
                                             batch_format="pandas")))
    assert list(b["id"]) == [0, 1, 2, 3, 4]
    b = next(iter(rtd.range(10).iter_batches(batch_size=5,
                                             batch_format="pyarrow")))
    assert b.num_rows == 5


def test_repartition_and_shuffle(rt):
    mat = rtd.range(100, parallelism=2).repartition(5).materialize()
    assert mat.num_blocks() == 5
    assert mat.count() == 100
    shuffled = rtd.range(50).random_shuffle(seed=7).take_all()
    ids = [r["id"] for r in shuffled]
    assert sorted(ids) == list(range(50))
    assert ids != list(range(50))


def test_sort(rt):
    ds = rtd.from_items([{"k": v} for v in [5, 3, 8, 1, 9, 2]]).sort("k")
    assert [r["k"] for r in ds.take_all()] == [1, 2, 3, 5, 8, 9]
    ds = rtd.from_items([{"k": v} for v in [5, 3, 8]]).sort("k", descending=True)
    assert [r["k"] for r in ds.take_all()] == [8, 5, 3]


def test_groupby_agg(rt):
    items = [{"g": i % 3, "v": i} for i in range(12)]
    ds = rtd.from_items(items, parallelism=3).groupby("g").sum("v")
    rows = {r["g"]: r["sum(v)"] for r in ds.take_all()}
    assert rows == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}


def test_global_aggregates(rt):
    ds = rtd.range(10)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == pytest.approx(4.5)


def test_join_inner_and_left(rt):
    import ray_tpu.data as rdata

    left = rdata.from_items(
        [{"id": i, "x": i * 10} for i in range(8)], parallelism=3)
    right = rdata.from_items(
        [{"id": i, "y": i * 100} for i in range(4, 12)], parallelism=2)

    rows = sorted(left.join(right, on="id").take_all(),
                  key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [4, 5, 6, 7]
    assert all(r["y"] == r["id"] * 100 and r["x"] == r["id"] * 10
               for r in rows)

    louter = sorted(left.join(right, on="id", how="left_outer").take_all(),
                    key=lambda r: r["id"])
    assert [r["id"] for r in louter] == list(range(8))
    assert louter[0]["y"] is None and louter[7]["y"] == 700


def test_join_left_outer_empty_right(rt):
    """One side filtered to nothing: outer joins still emit its columns as
    nulls (schema carried via bundle metadata)."""
    import ray_tpu.data as rdata

    left = rdata.from_items([{"id": i, "x": i} for i in range(4)],
                            parallelism=2)
    right = rdata.from_items([{"id": i, "y": i} for i in range(4)],
                             parallelism=2).filter(lambda r: r["id"] > 99)
    rows = sorted(left.join(right, on="id", how="left_outer").take_all(),
                  key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [0, 1, 2, 3]
    assert all(r["y"] is None for r in rows)


def test_join_string_keys_cross_process(rt):
    """String keys must route to the same partition on both sides even
    though the two sides' partition tasks run in different worker processes
    (builtin hash() is per-process randomized)."""
    import ray_tpu.data as rdata

    names = [f"user-{i}" for i in range(12)]
    left = rdata.from_items([{"k": n, "x": i} for i, n in enumerate(names)],
                            parallelism=3)
    right = rdata.from_items([{"k": n, "y": i * 2}
                              for i, n in enumerate(names)], parallelism=2)
    rows = left.join(right, on="k", num_partitions=4).take_all()
    assert len(rows) == 12
    assert all(r["y"] == r["x"] * 2 for r in rows)


def test_join_different_key_names(rt):
    import ray_tpu.data as rdata

    left = rdata.from_items([{"k": i} for i in range(5)], parallelism=2)
    right = rdata.from_items([{"j": i, "v": -i} for i in range(3, 8)],
                             parallelism=2)
    rows = sorted(left.join(right, on="k", right_on="j").take_all(),
                  key=lambda r: r["k"])
    assert [r["k"] for r in rows] == [3, 4]
    assert [r["v"] for r in rows] == [-3, -4]


def test_stats_after_execution(rt):
    import ray_tpu.data as rdata

    ds = rdata.range(100, parallelism=4).map_batches(
        lambda b: {"id": b["id"] * 2})
    assert "Plan:" in ds.stats()
    ds.take_all()
    s = ds.stats()
    assert "rows" in s and "Total:" in s
    assert "100 rows" in s  # terminal op saw every row


def test_union_zip(rt):
    a = rtd.from_items([{"x": 1}, {"x": 2}])
    b = rtd.from_items([{"x": 3}])
    assert sorted(r["x"] for r in a.union(b).take_all()) == [1, 2, 3]

    c = rtd.from_items([{"y": 10}, {"y": 20}])
    rows = a.zip(c).take_all()
    assert sorted((r["x"], r["y"]) for r in rows) == [(1, 10), (2, 20)]


def test_parquet_roundtrip(rt, tmp_path):
    ds = rtd.range(50, parallelism=2).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    paths = ds.write_parquet(str(tmp_path / "out"))
    assert len(paths) >= 1
    back = rtd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    assert back.sum("sq") == sum(i * i for i in range(50))


def test_csv_json_roundtrip(rt, tmp_path):
    ds = rtd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
    ds.write_csv(str(tmp_path / "csv"))
    assert rtd.read_csv(str(tmp_path / "csv")).count() == 2

    ds.write_json(str(tmp_path / "json"))
    back = rtd.read_json(str(tmp_path / "json")).take_all()
    assert sorted(r["a"] for r in back) == [1, 2]


def test_read_text_binary(rt, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("hello\nworld\n")
    assert [r["text"] for r in rtd.read_text(str(p)).take_all()] == \
        ["hello", "world"]
    assert rtd.read_binary_files(str(p)).take_all()[0]["bytes"] == \
        b"hello\nworld\n"


def test_tensor_columns_numpy(rt):
    arr = np.arange(24, dtype=np.float32).reshape(6, 2, 2)
    ds = rtd.from_numpy(arr, column="img")
    batch = next(iter(ds.iter_batches(batch_size=6)))
    np.testing.assert_array_equal(batch["img"], arr)


def test_from_pandas_arrow(rt):
    import pandas as pd
    df = pd.DataFrame({"a": [1, 2, 3]})
    assert rtd.from_pandas(df).sum("a") == 6


def test_iter_jax_batches(rt):
    import jax.numpy as jnp
    batches = list(rtd.range(16).iter_jax_batches(batch_size=8))
    assert len(batches) == 2
    assert isinstance(batches[0]["id"], jnp.ndarray)
    total = sum(int(b["id"].sum()) for b in batches)
    assert total == sum(range(16))


def test_split(rt):
    parts = rtd.range(100, parallelism=4).split(2)
    counts = [p.count() for p in parts]
    assert sum(counts) == 100
    assert all(c > 0 for c in counts)


def test_streaming_split_cross_process(rt):
    splits = rtd.range(40, parallelism=4).streaming_split(2)

    @ray_tpu.remote
    def consume(it):
        return sorted(r["id"] for r in it.iter_rows())

    out = ray_tpu.get([consume.remote(s) for s in splits], timeout=120)
    all_ids = sorted(out[0] + out[1])
    assert all_ids == list(range(40))
    assert out[0] and out[1]


def test_local_shuffle_buffer(rt):
    ids = [int(b["id"][0]) for b in rtd.range(32).iter_batches(
        batch_size=1, local_shuffle_buffer_size=16, local_shuffle_seed=3)]
    assert sorted(ids) == list(range(32))
    assert ids != list(range(32))


def test_select_drop_rename_add(rt):
    ds = rtd.from_items([{"a": 1, "b": 2}])
    assert ds.select_columns(["a"]).take_all() == [{"a": 1}]
    assert ds.drop_columns(["a"]).take_all() == [{"b": 2}]
    assert ds.rename_columns({"a": "z"}).take_all() == [{"z": 1, "b": 2}]
    out = ds.add_column("c", lambda b: b["a"] + b["b"])
    assert out.take_all() == [{"a": 1, "b": 2, "c": 3}]


def test_executor_error_propagates(rt):
    def boom(b):
        raise ValueError("kaboom")

    with pytest.raises(Exception, match="kaboom"):
        rtd.range(10).map_batches(boom).take_all()


def test_hash_repartition_colocates_keys(rt):
    """repartition(key=...) is a hash shuffle: all rows with equal keys land
    in the same output block (reference hash_shuffle.py semantics)."""
    ds = rtd.range(1000, parallelism=8).map_batches(
        lambda b: {"id": b["id"], "key": b["id"] % 7})
    out = ds.repartition(4, key="key")
    per_block = out.map_batches(
        lambda b: {"keys": np.unique(np.asarray(b["key"])),
                   "n": np.full(len(np.unique(np.asarray(b["key"]))),
                                len(b["key"]))})
    rows = per_block.take_all()
    seen: dict = {}
    for r in rows:
        assert r["keys"] not in seen, \
            f"key {r['keys']} appears in multiple output blocks"
        seen[r["keys"]] = True
    assert len(seen) == 7
    assert out.count() == 1000


def test_repartition_single_block(rt):
    """n=1 shuffle: the shard is the input block itself (regression: the
    num_returns=1 path wrapped the 1-element shard list as one object)."""
    assert rtd.range(50, parallelism=4).repartition(1).count() == 50
    ds = rtd.range(20, parallelism=2).map_batches(
        lambda b: {"g": b["id"] % 2, "v": b["id"]})
    one = ds.groupby("g").sum("v").take_all()
    assert sum(r["sum(v)"] for r in one) == sum(range(20))

def test_streaming_read_incremental(rt):
    """Read tasks stream blocks through ObjectRefGenerators: the first
    output bundle is consumable while the datasource is still producing
    later blocks (VERDICT r2 #5's Data-side done-bar)."""
    import time as _time

    from ray_tpu.data.block import block_from_dict
    from ray_tpu.data.datasource import Datasource, ReadTask

    class SlowSource(Datasource):
        def get_read_tasks(self, parallelism):
            def read():
                for i in range(4):
                    if i:
                        _time.sleep(2.0)  # later blocks trickle out
                    yield block_from_dict({"x": [i] * 10})
            return [ReadTask(read_fn=read, num_rows=40)]

    from ray_tpu.core.config import get_config
    get_config().data_streaming_reads = True
    try:
        ds = rtd.read_datasource(SlowSource())
        t0 = _time.monotonic()
        it = iter(ds.iter_batches(batch_size=10, batch_format="numpy"))
        first = next(it)
        first_latency = _time.monotonic() - t0
        assert sorted(first["x"].tolist()) == [0] * 10
        # the source still has ~6s of sleeps left when batch 0 arrives; the
        # wide margin keeps a loaded CI box from flaking this
        assert first_latency < 5.0, f"first batch took {first_latency:.1f}s"
        rest = list(it)
        assert sum(len(b["x"]) for b in rest) == 30
    finally:
        get_config().data_streaming_reads = False

def test_expressions_filter_and_with_column(rt):
    from ray_tpu.data import col, lit

    ds = ray_tpu.data.from_items(
        [{"x": i, "tag": "a" if i % 2 == 0 else "b"} for i in range(10)])
    out = ds.filter((col("x") > 3) & (col("tag") == lit("a"))).take_all()
    assert [r["x"] for r in out] == [4, 6, 8]

    out = ds.with_column("y", col("x") * 2 + 1).take(3)
    assert [r["y"] for r in out] == [1, 3, 5]

    out = ds.with_column("z", lit(7)).take(2)
    assert [r["z"] for r in out] == [7, 7]


def test_expression_filter_fuses_into_read(rt):
    """The pushdown bar (VERDICT r3 item 6): an expression filter on a
    fresh read must fuse INTO the read stage in the optimized plan."""
    from ray_tpu.data import col
    from ray_tpu.data.logical import FusedRead, LogicalPlan, optimize

    ds = ray_tpu.data.range(100).filter(col("id") >= 90)
    plan = optimize(LogicalPlan(ds._terminal))
    ops = plan.ops()
    assert len(ops) == 1 and isinstance(ops[0], FusedRead), str(plan)
    assert [r["id"] for r in ds.take_all()] == list(range(90, 100))


def test_preprocessors_fit_transform(rt):
    import numpy as np

    from ray_tpu.data.preprocessors import (
        Chain,
        Concatenator,
        MinMaxScaler,
        OneHotEncoder,
        StandardScaler,
    )

    items = [{"a": float(i), "b": float(10 - i), "cat": "xy"[i % 2]}
             for i in range(10)]
    ds = ray_tpu.data.from_items(items)

    scaler = StandardScaler(["a"]).fit(ds)
    out = scaler.transform(ds).take_all()
    vals = np.array([r["a"] for r in out])
    assert abs(vals.mean()) < 1e-9 and abs(vals.std(ddof=1) - 1.0) < 1e-9

    chain = Chain(MinMaxScaler(["a", "b"]), OneHotEncoder(["cat"]),
                  Concatenator(["a", "b", "cat_x", "cat_y"],
                               output_column_name="f"))
    out = chain.fit_transform(ds).take_all()
    feats = [np.asarray(r["f"]) for r in out]
    assert feats[0].shape == (4,)
    assert feats[0][0] == 0.0 and feats[-1][0] == 1.0
    # one-hot columns are exclusive
    assert all((f[2] + f[3]) == 1.0 for f in feats)


def test_read_webdataset(rt, tmp_path):
    import io
    import json as jsonlib
    import tarfile

    shard = tmp_path / "shard-000000.tar"
    with tarfile.open(shard, "w") as tf:
        for i in range(5):
            payload = f"img-bytes-{i}".encode()
            info = tarfile.TarInfo(f"{i:04d}.jpg")
            info.size = len(payload)
            tf.addfile(info, io.BytesIO(payload))
            meta = jsonlib.dumps({"label": i}).encode()
            info = tarfile.TarInfo(f"{i:04d}.json")
            info.size = len(meta)
            tf.addfile(info, io.BytesIO(meta))

    rows = ray_tpu.data.read_webdataset(str(shard)).take_all()
    assert len(rows) == 5
    assert rows[0]["__key__"] == "0000"
    assert rows[2]["jpg"] == b"img-bytes-2"
    assert rows[3]["json"]["label"] == 3


def test_read_sql(rt, tmp_path):
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO t VALUES (?, ?)",
                     [(i, f"n{i}") for i in range(20)])
    conn.commit()
    conn.close()

    ds = ray_tpu.data.read_sql(
        "SELECT id, name FROM t", lambda: sqlite3.connect(db),
        parallelism_column="id", parallelism=4)
    rows = sorted(ds.take_all(), key=lambda r: r["id"])
    assert len(rows) == 20
    assert rows[7] == {"id": 7, "name": "n7"}


def test_from_huggingface(rt):
    datasets = pytest.importorskip("datasets")
    hf = datasets.Dataset.from_dict({"x": list(range(8)), "y": ["a"] * 8})
    rows = ray_tpu.data.from_huggingface(hf).take_all()
    assert len(rows) == 8 and rows[3]["x"] == 3


@pytest.mark.slow
def test_distributed_hash_shuffle_1gb_two_nodes():
    """VERDICT r2 #7: shuffle >=1 GB across a 2-node cluster under per-node
    object-store caps. The shuffle moves shard REFS (map emits one ref per
    output partition; reduce concats) — partition data never passes through
    the driver (reference hash_shuffle.py map/reduce split)."""
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config

    ray_tpu.shutdown()
    # GiB-scale arrow ops monopolize this 1-core box for seconds at a time;
    # the default health-check budget declares the (in-process) node dead
    # mid-shuffle. Loosen it for this test only.
    cfg = get_config()
    saved = (cfg.health_check_timeout_s, cfg.health_check_failure_threshold)
    cfg.health_check_timeout_s = 120.0
    cfg.health_check_failure_threshold = 120
    cluster = Cluster()
    cap = 3 * (1 << 30) // 2  # 1.5 GiB per node store
    cluster.add_node(num_cpus=2, object_store_memory=cap)
    cluster.add_node(num_cpus=2, object_store_memory=cap)
    ray_tpu.init(address=cluster.address)
    try:
        n_rows = 1 << 26  # 64M rows -> id+key columns = 1 GiB into shuffle
        n_keys = 64
        ds = rtd.range(n_rows, parallelism=16).map_batches(
            lambda b: {"id": b["id"], "key": b["id"] % n_keys})
        out = ds.repartition(8, key="key")
        # verify without materializing at the driver: per-output-block key
        # sets (small) + conserved row count
        per_block = out.map_batches(
            lambda b: {"keys": np.unique(np.asarray(b["key"]))})
        key_sets = [set(np.atleast_1d(r["keys"]).tolist())
                    for r in per_block.take_all()]
        merged: set = set()
        # a key appears in exactly one output block (keys within one output
        # block may span multiple source blocks -> true shuffle happened)
        flat = [k for s in key_sets for k in set(s)]
        assert len(flat) == len(set(flat)), "key split across output blocks"
        for s in key_sets:
            merged |= s
        assert merged == set(range(n_keys))
        assert out.count() == n_rows
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        cfg.health_check_timeout_s, cfg.health_check_failure_threshold = saved





def test_read_delta_native(ray_start_regular, tmp_path):
    """Delta Lake without the deltalake library: parquet files + a
    _delta_log JSON fold, including remove actions (compaction)."""
    import json as jsonlib

    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu import data as rdata

    table = str(tmp_path / "delta")
    os.makedirs(os.path.join(table, "_delta_log"))
    pq.write_table(pa.table({"x": [1, 2]}), os.path.join(table, "a.parquet"))
    pq.write_table(pa.table({"x": [3, 4]}), os.path.join(table, "b.parquet"))
    pq.write_table(pa.table({"x": [5, 6]}), os.path.join(table, "c.parquet"))
    with open(os.path.join(table, "_delta_log",
                           "00000000000000000000.json"), "w") as f:
        f.write(jsonlib.dumps({"add": {"path": "a.parquet"}}) + "\n")
        f.write(jsonlib.dumps({"add": {"path": "b.parquet"}}) + "\n")
    with open(os.path.join(table, "_delta_log",
                           "00000000000000000001.json"), "w") as f:
        # version 1 compacts a+b into c
        f.write(jsonlib.dumps({"remove": {"path": "a.parquet"}}) + "\n")
        f.write(jsonlib.dumps({"remove": {"path": "b.parquet"}}) + "\n")
        f.write(jsonlib.dumps({"add": {"path": "c.parquet"}}) + "\n")

    ds = rdata.read_delta(table)
    rows = sorted(r["x"] for r in ds.take_all())
    assert rows == [5, 6]  # only the live snapshot


def test_external_datasources_gate_cleanly(ray_start_regular):
    """lance/iceberg/bigquery/mongo need client libraries this image does
    not ship: the readers must raise ImportError with the package name
    (reference datasource breadth, gated)."""
    from ray_tpu import data as rdata

    for fn, pkg, args in (
            (rdata.read_lance, "lance", ("/tmp/x.lance",)),
            (rdata.read_iceberg, "pyiceberg", ("db.tbl",)),
            (rdata.read_bigquery, "bigquery", ("proj",)),
            (rdata.read_mongo, "pymongo",
             ("mongodb://h", "db", "coll"))):
        try:
            __import__(pkg if pkg != "bigquery" else "google.cloud.bigquery")
            continue  # installed: gating not applicable
        except ImportError:
            pass
        with pytest.raises(ImportError, match=pkg):
            fn(*args)
