"""Client-side runtime for remote drivers (`ray_tpu://host:port`).

TPU-native analog of the reference's Ray Client worker
(/root/reference/python/ray/util/client/worker.py): implements the same
runtime interface the local WorkerRuntime exposes to the API layer
(submit_task / submit_actor_creation / submit_actor_task / put / get / wait),
but every operation is an RPC to a ClientServer, which runs a real driver
inside the cluster. No shared memory with the cluster is needed.
"""

from __future__ import annotations

import threading

import cloudpickle

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.rpc import RpcClient


class _ClientRefCounter:
    """Local-ref bookkeeping: when the last client-side ObjectRef for an oid
    dies, release the server-side pin (batched)."""

    def __init__(self, runtime: "ClientRuntime"):
        self._rt = runtime
        self._counts: dict = {}
        self._lock = threading.Lock()

    def add_local_ref(self, oid):
        with self._lock:
            self._counts[oid] = self._counts.get(oid, 0) + 1

    def remove_local_ref(self, oid):
        release = False
        with self._lock:
            c = self._counts.get(oid, 0) - 1
            if c <= 0:
                self._counts.pop(oid, None)
                release = True
            else:
                self._counts[oid] = c
        if release:
            self._rt._release(oid)

    # api.cancel probes these; harmless defaults for client mode
    def is_owned(self, oid) -> bool:
        return False


class _CpProxy:
    """cp_client lookalike forwarding through the server's driver, so state
    APIs / named actors / kill work unchanged in client mode."""

    def __init__(self, runtime: "ClientRuntime"):
        self._rt = runtime

    def call(self, method: str, body=None, timeout: float | None = 30.0):
        return self._rt._call("call_cp", {"method": method, "body": body,
                                          "timeout": timeout},
                              timeout=(timeout or 30.0) + 10.0)

    def call_with_retry(self, method: str, body=None,
                        timeout: float | None = 30.0, retries: int = 3):
        last = None
        for _ in range(retries + 1):
            try:
                return self.call(method, body, timeout)
            except Exception as e:  # noqa: BLE001
                last = e
        raise last

    def notify(self, method: str, body=None):
        self.call(method, body, timeout=30.0)


class _StubTaskManager:
    def get_pending_spec(self, task_id):
        return None


class ClientRuntime:
    mode = "client"

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self._client = RpcClient((host, int(port)), name="ray-client")
        reply = self._client.call("connect", {}, timeout=30.0)
        self._session = reply["session_id"]
        self.job_id = reply["job_id"]
        self.node_id = None
        self.worker_id = None
        self.cp_addr = (host, int(port))
        self.addr = ("client", 0)
        self.reference_counter = _ClientRefCounter(self)
        self.cp_client = _CpProxy(self)
        self.task_manager = _StubTaskManager()
        self._fn_ids: dict[int, str] = {}  # id(fn) -> server fn_id
        self._fn_lock = threading.Lock()

    # -- plumbing -------------------------------------------------------
    def _call(self, method: str, body: dict, timeout: float = 60.0):
        body["session"] = self._session
        return self._client.call(method, body, timeout=timeout)

    def _release(self, oid):
        try:
            self._call("release", {"oids": [oid.binary()]}, timeout=10.0)
        except Exception:
            pass

    def _register(self, fn) -> str:
        with self._fn_lock:
            fn_id = self._fn_ids.get(id(fn))
        if fn_id is not None:
            return fn_id
        blob = cloudpickle.dumps(fn)
        fn_id = self._call("register_fn", {"blob": blob}, timeout=60.0)["fn_id"]
        with self._fn_lock:
            self._fn_ids[id(fn)] = fn_id
        return fn_id

    def _pack_args(self, args, kwargs) -> bytes:
        from ray_tpu.client.server import _RefPlaceholder

        def swap(x):
            if isinstance(x, ObjectRef):
                return _RefPlaceholder(x.id().binary())
            return x
        return cloudpickle.dumps(
            (tuple(swap(a) for a in args),
             {k: swap(v) for k, v in kwargs.items()}))

    def _mk_refs(self, ref_infos) -> list[ObjectRef]:
        return [ObjectRef(oid, owner, tuple(addr) if addr else None)
                for oid, owner, addr in ref_infos]

    # -- runtime interface ---------------------------------------------
    def put(self, value, **_kw) -> ObjectRef:
        reply = self._call("put", {"data": cloudpickle.dumps(value)})
        return self._mk_refs(reply["refs"])[0]

    def get(self, refs, timeout: float | None = None):
        reply = self._call(
            "get", {"oids": [r.id().binary() for r in refs],
                    "timeout": timeout},
            timeout=(timeout or 3600.0) + 30.0)
        if "error" in reply:
            raise cloudpickle.loads(reply["error"])
        return cloudpickle.loads(reply["data"])

    def wait(self, refs, num_returns: int = 1, timeout: float | None = None):
        reply = self._call(
            "wait", {"oids": [r.id().binary() for r in refs],
                     "num_returns": num_returns, "timeout": timeout},
            timeout=(timeout or 3600.0) + 30.0)
        by_bin = {r.id().binary(): r for r in refs}
        return ([by_bin[b] for b in reply["ready"]],
                [by_bin[b] for b in reply["pending"]])

    def submit_task(self, fn, args, kwargs, **opts) -> list[ObjectRef]:
        reply = self._call("task", {
            "fn_id": self._register(fn),
            "args": self._pack_args(args, kwargs),
            "opts": opts})
        return self._mk_refs(reply["refs"])

    def submit_actor_creation(self, cls, args, kwargs, *, actor_id, **opts):
        self._call("actor_create", {
            "fn_id": self._register(cls),
            "actor_id": actor_id,
            "args": self._pack_args(args, kwargs),
            "opts": opts})
        return actor_id

    def submit_actor_task(self, actor_id, method: str, args, kwargs,
                          **opts) -> list[ObjectRef]:
        reply = self._call("actor_call", {
            "actor_id": actor_id, "method": method,
            "args": self._pack_args(args, kwargs), "opts": opts})
        return self._mk_refs(reply["refs"])

    def as_future(self, ref):
        from concurrent.futures import Future
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.get([ref], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        threading.Thread(target=run, daemon=True).start()
        return fut

    def in_actor(self) -> bool:
        return False

    def current_task_id(self):
        return self.job_id  # stable per-connection scope for collectives

    def yield_exec_slot(self):
        import contextlib
        return contextlib.nullcontext()

    def shutdown(self):
        try:
            self._call("disconnect", {}, timeout=10.0)
        except Exception:
            pass
        self._client.close()
