"""Dashboard endpoint tests (reference: dashboard/modules/* — state,
train, serve, reporter/profile endpoints; here one aiohttp head serves
them all from the CP's state)."""

import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def dash(ray_start_module):
    from ray_tpu.dashboard import start_dashboard
    d = start_dashboard(port=0)
    # fast sampler for the timeseries test
    d._timeseries.period_s = 0.5
    yield d
    d.stop()


def _get(d, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{d.port}{path}", timeout=30) as r:
        body = r.read()
    try:
        return json.loads(body)
    except ValueError:
        return body.decode()


def test_dashboard_core_sections(dash):
    @ray_tpu.remote
    class Marker:
        def ping(self):
            return 1

    a = Marker.options(name="dash-marker").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1

    nodes = _get(dash, "/api/nodes")
    assert nodes and nodes[0].get("alive", True)
    actors = _get(dash, "/api/actors")
    assert any("Marker" in str(r.get("class_name", "")) for r in actors)
    assert isinstance(_get(dash, "/api/pgs"), list)
    assert isinstance(_get(dash, "/api/tasks"), list)
    html = _get(dash, "/")
    assert "dashboard" in html and "sparkline" in html
    ray_tpu.kill(a)


def test_dashboard_node_detail(dash):
    nodes = _get(dash, "/api/nodes")
    nid = nodes[0]["node_id"]
    detail = _get(dash, f"/api/node/{nid}")
    assert detail["node_id"].startswith(nid[:8])
    assert "metrics" in detail and "actors" in detail
    # unknown node -> 404
    with pytest.raises(urllib.error.HTTPError):
        _get(dash, "/api/node/ffffffffffff")


def test_dashboard_timeseries(dash):
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        ts = _get(dash, "/api/timeseries")
        if len(ts) >= 2:
            break
        time.sleep(0.5)
    assert len(ts) >= 2
    assert "nodes_alive" in ts[-1] and ts[-1]["nodes_alive"] >= 1


def test_dashboard_profile(dash):
    out = _get(dash, "/api/profile?duration=1")
    assert out["rounds"] >= 1
    assert out["collapsed"], "no stacks sampled"
    # collapsed format: proc;thread;file:func ... count
    frame, count = out["collapsed"][0].rsplit(" ", 1)
    assert ";" in frame and int(count) >= 1


def test_dashboard_train_run_visible(dash, tmp_path):
    """A JaxTrainer run publishes controller state to the CP KV and the
    dashboard's train section shows it end-to-end."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        import ray_tpu.train as train
        for step in range(3):
            train.report({"step": step, "loss": 1.0 / (step + 1)})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2, use_tpu=False),
        run_config=RunConfig(name="dash-run",
                             storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    runs = _get(dash, "/api/train")
    mine = [r for r in runs if r["name"] == "dash-run"]
    assert mine, f"train run not visible: {runs}"
    assert mine[0]["state"] == "FINISHED"
    assert mine[0]["num_workers"] == 2
    assert mine[0]["latest_metrics"]["step"] == 2


def test_dashboard_serve_section(dash):
    from ray_tpu import serve

    @serve.deployment
    def hello(payload):
        return {"ok": True}

    serve.run(hello.bind(), name="dash-app", route_prefix="/hello")
    try:
        deadline = time.monotonic() + 30
        rows = []
        while time.monotonic() < deadline:
            rows = _get(dash, "/api/serve")
            if rows and any(r.get("replicas", 0) >= 1 for r in rows):
                break
            time.sleep(0.5)
        assert rows, "no serve deployments visible"
        row = rows[0]
        assert row["replicas"] >= 1
        assert "queue_lens" in row
        # plain function deployment: engine column present but empty
        assert row.get("engine") is None
    finally:
        serve.shutdown()


def test_dashboard_serve_engine_stats_and_metrics(dash):
    """LLM deployments surface engine counters (steps/prefills/tokens_out/
    shed + prefix-cache hit/miss/evict) in the serve view next to the
    queue lens, and the replica's pushed gauges ride the dashboard's
    Prometheus scrape."""
    from ray_tpu import serve
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig, build_openai_app

    cfg = LLMConfig(model_config=llama.llama_tiny(vocab_size=512),
                    max_batch_size=4, page_size=16, num_pages=64,
                    max_prompt_len=64, max_seq_len=128, max_tokens=4)
    serve.run(build_openai_app(cfg, route_prefix="/v1"),
              name="dash-llm", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{proxy.port}/v1/completions",
            data=json.dumps({"prompt": "the quick brown fox jumps",
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200

        engine = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            rows = _get(dash, "/api/serve")
            llm_rows = [r for r in rows if r.get("engine")]
            if llm_rows:
                engine = llm_rows[0]["engine"][0]
                if engine and engine.get("tokens_out", 0) >= 4:
                    break
            time.sleep(0.5)
        assert engine, "no engine stats in the serve view"
        for key in ("steps", "prefills", "tokens_out", "shed_expired",
                    "prefix_hits", "prefix_misses", "prefix_cached_pages",
                    "prefix_evictions"):
            assert key in engine, f"missing engine stat {key}"
        assert engine["prefills"] >= 1
        assert engine["prefix_misses"] + engine["prefix_hits"] >= 1

        # the /api/serve probe itself flushed the gauges through the
        # registry pipeline; the Prometheus scrape must aggregate them
        scrape = _get(dash, "/metrics")
        assert "ray_tpu_llm_engine" in scrape
        assert 'stat="prefix_hits"' in scrape
    finally:
        serve.shutdown()


def test_dashboard_autoscaler_section(dash):
    """Instance lifecycle rows published by a live autoscaler appear in
    the dashboard's autoscaler section."""
    from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
    from ray_tpu.autoscaler.node_provider import FakeNodeProvider
    from ray_tpu.core import api

    rt = api._get_runtime()
    provider = FakeNodeProvider(rt.cp_addr, inproc_workers=True)
    scaler = Autoscaler(rt.cp_addr, provider,
                        AutoscalerConfig(min_workers=1, max_workers=1,
                                         node_resources={"CPU": 1},
                                         idle_timeout_s=300.0))
    try:
        deadline = time.monotonic() + 60
        rows = []
        while time.monotonic() < deadline:
            scaler.update()
            scaler._publish_state()
            rows = _get(dash, "/api/autoscaler")
            if rows and rows[0]["state"] == "RAY_RUNNING":
                break
            time.sleep(0.5)
        assert rows and rows[0]["state"] == "RAY_RUNNING"
        assert rows[0]["history"], "no lifecycle history recorded"
    finally:
        for name in provider.non_terminated_nodes():
            provider.terminate_node(name)


def test_dashboard_trace_views(dash):
    """Spans reported to the CP surface in the traces section, the JSON
    detail endpoint, and the per-trace waterfall page."""
    from ray_tpu.core import api

    rt = api._get_runtime()
    t0 = time.time()
    tid = "feed" * 8
    root = {"trace_id": tid, "span_id": "ab" * 8, "parent_id": None,
            "name": "task.submit:demo", "kind": "submit",
            "start": t0, "end": t0 + 1.0, "status": "ok", "pid": 7,
            "attrs": {"task_id": "t1"}}
    child = {"trace_id": tid, "span_id": "cd" * 8, "parent_id": "ab" * 8,
             "name": "task.run:demo", "kind": "server",
             "start": t0 + 0.1, "end": t0 + 0.9, "status": "error",
             "pid": 8, "attrs": {"error": "ValueError"}}
    rt.cp_client.notify("report_spans", {"spans": [root, child]})

    deadline = time.monotonic() + 20
    rows = []
    while time.monotonic() < deadline:
        rows = [r for r in _get(dash, "/api/traces")
                if r["trace_id"] == tid]
        if rows:
            break
        time.sleep(0.25)
    assert rows and rows[0]["num_spans"] == 2
    assert rows[0]["name"] == "task.submit:demo"

    detail = _get(dash, f"/api/trace/{tid[:8]}")  # prefix lookup
    assert detail["trace_id"] == tid and len(detail["spans"]) == 2

    html = _get(dash, f"/trace/{tid}")
    assert "task.submit:demo" in html and "task.run:demo" in html
    assert "#c33" in html, "error span not highlighted"

    with pytest.raises(urllib.error.HTTPError):
        _get(dash, "/api/trace/00000000deadbeef")
