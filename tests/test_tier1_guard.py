"""Tier-1 drift guard: chaos and heavyweight multi-node tests must carry
`@pytest.mark.slow` so the tier-1 gate (`pytest -m 'not slow'`) stays fast
and deterministic.

The guard is static (AST scan, no imports, no collection side effects): a
test function that references a chaos harness class (WorkerKiller /
NodeKiller / FaultSchedule) or builds a 3+-node in-process Cluster belongs
in the slow tier. The allowlist freezes the seed-era exceptions — do NOT
grow it for new tests; mark them slow instead.
"""

import ast
import pathlib

CHAOS_NAMES = {"WorkerKiller", "NodeKiller", "FaultSchedule"}

# Frozen exceptions. Each entry is a deliberate tier-1 resident:
ALLOWLIST = {
    # seed-era tier-1 chaos coverage, bounded (< ~30s each) and load-bearing
    # for the lineage/retry acceptance of earlier PRs
    "test_node_killer_lineage_reconstruction",
    "test_chaos_worker_killer_workload_completes",
    # pure unit tests of the chaos harnesses themselves (fake procs / no
    # cluster, sub-second)
    "test_faultschedule_validates_and_fires_rpc_faults",
    "test_worker_killer_max_kills",
}


def _is_slow_marker(dec: ast.expr) -> bool:
    """True for `@pytest.mark.slow` (bare or called)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    return (isinstance(dec, ast.Attribute) and dec.attr == "slow"
            and isinstance(dec.value, ast.Attribute)
            and dec.value.attr == "mark")


def test_chaos_and_multinode_tests_are_slow_marked():
    offenders = []
    here = pathlib.Path(__file__).parent
    for path in sorted(here.glob("test_*.py")):
        if path.name == pathlib.Path(__file__).name:
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test"):
                continue
            if node.name in ALLOWLIST:
                continue
            if any(_is_slow_marker(d) for d in node.decorator_list):
                continue
            names = {n.id for n in ast.walk(node)
                     if isinstance(n, ast.Name)}
            attrs = {n.attr for n in ast.walk(node)
                     if isinstance(n, ast.Attribute)}
            uses_chaos = (names | attrs) & CHAOS_NAMES
            add_node_calls = sum(
                1 for c in ast.walk(node)
                if isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "add_node")
            if uses_chaos:
                offenders.append(
                    f"{path.name}::{node.name} (uses {sorted(uses_chaos)})")
            elif add_node_calls >= 3:
                offenders.append(
                    f"{path.name}::{node.name} "
                    f"({add_node_calls} add_node calls)")
    assert not offenders, (
        "chaos/multi-node tests must be @pytest.mark.slow so tier-1 stays "
        "fast (or, exceptionally, added to the frozen ALLOWLIST in "
        f"{pathlib.Path(__file__).name}):\n  " + "\n  ".join(offenders))
