"""TPU slice topology detection.

TPU-native generalization of the reference's TPU accelerator manager
(/root/reference/python/ray/_private/accelerators/tpu.py:114 topology inference,
:199 detection): reads the TPU runtime environment variables (and, on GCE, the
metadata server) to label this host with its slice identity, so the scheduler
can do ICI-aware placement and atomic slice gang scheduling (SURVEY.md §7
phase 4).

A fake provider (``RAY_TPU_FAKE_TOPOLOGY`` env, JSON) lets multi-slice
scheduling tests run on CPU hosts — the test keystone called out in
SURVEY.md §4.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


# chips per host for common accelerator types (ref: tpu.py topology tables)
_CHIPS_PER_HOST = {
    "v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 4, "v5e": 4, "v6e": 4,
}


@dataclass
class SliceTopology:
    slice_name: str
    pod_type: str       # e.g. "v5p-64"
    topology: str       # e.g. "2x2x4"
    worker_id: int      # this host's index within the slice
    num_hosts: int
    chips_per_host: int

    @property
    def total_chips(self) -> int:
        return self.num_hosts * self.chips_per_host


def _accelerator_chips_per_host(pod_type: str) -> int:
    gen = pod_type.split("-")[0].lower()
    return _CHIPS_PER_HOST.get(gen, 4)


def detect_local_topology() -> SliceTopology | None:
    """Detect this host's slice membership, or None if not a TPU host."""
    fake = os.environ.get("RAY_TPU_FAKE_TOPOLOGY")
    if fake:
        d = json.loads(fake)
        return SliceTopology(
            slice_name=d.get("slice_name", "fake-slice"),
            pod_type=d.get("pod_type", "v5p-8"),
            topology=d.get("topology", "2x2x1"),
            worker_id=int(d.get("worker_id", 0)),
            num_hosts=int(d.get("num_hosts", 1)),
            chips_per_host=int(d.get("chips_per_host", 4)),
        )
    # TPU VM runtime env vars (ref: tpu.py TPU_* env detection)
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    if accel is None:
        # tunneled dev chip (axon PJRT plugin): no TPU VM metadata env, but
        # the plugin's generation var marks a single attached chip. Without
        # this, whether the node advertises a TPU resource depends on which
        # login-profile vars happened to materialize.
        gen = os.environ.get("PALLAS_AXON_TPU_GEN")
        if gen:
            return SliceTopology(
                slice_name=os.environ.get("HOSTNAME", "local-slice"),
                pod_type=f"{gen}-tunnel", topology="1x1",
                worker_id=0, num_hosts=1, chips_per_host=1)
        return None
    worker_id = int(os.environ.get("TPU_WORKER_ID", "0"))
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    num_hosts = len(hostnames.split(",")) if hostnames else 1
    slice_name = os.environ.get("TPU_NAME", os.environ.get("HOSTNAME", "local-slice"))
    chips = _accelerator_chips_per_host(accel)
    topology = os.environ.get("TPU_TOPOLOGY", "")
    return SliceTopology(
        slice_name=slice_name, pod_type=accel, topology=topology,
        worker_id=worker_id, num_hosts=num_hosts, chips_per_host=chips,
    )


def slice_hosts(pod_type: str) -> int:
    """Number of hosts in a full slice of the given pod type, e.g. v5p-64 → 8
    (4 chips/host on v5p; the suffix counts cores on v2-v4 and chips on v5+)."""
    try:
        n = int(pod_type.split("-")[-1])
    except ValueError:
        return 1
    return max(1, n // _accelerator_chips_per_host(pod_type))
