"""Streaming generator returns (``num_returns="streaming"``).

TPU-native analog of the reference's streaming-generator protocol
(/root/reference/src/ray/protobuf/core_worker.proto:513
``ReportGeneratorItemReturns`` + the stream bookkeeping in
src/ray/core_worker/task_manager.cc): a task or actor method whose function
is a generator reports each yielded value to its owner AS IT IS PRODUCED;
the owner hands out an :class:`ObjectRefGenerator` whose ``next()`` blocks
for the next item's ref. The executor applies backpressure — at most
``streaming_backpressure_items`` unacknowledged items in flight — so a fast
producer cannot flood a slow consumer (reference:
``generator_backpressure_num_objects``).

Item identity is deterministic (``ObjectID.for_return(task_id, index+1)``),
so a retried generator re-produces the same ids and the owner's cursor is
unaffected; stale-attempt reports are dropped exactly like stale task
replies. If the producing task fails terminally, the stream is failed: the
consumer's next ``next()`` returns a ref holding the error.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID, TaskID

if TYPE_CHECKING:  # pragma: no cover
    from ray_tpu.core.task_spec import TaskSpec


class _Stream:
    """Owner-side state of one generator task's output stream."""

    def __init__(self, task_id: TaskID):
        self.task_id = task_id
        self.items: dict[int, ObjectID] = {}   # index -> ready object
        self.total: int | None = None          # set by the done marker
        self.cursor = 0                        # next index to hand out
        self.cv = threading.Condition()

    def put(self, index: int, oid: ObjectID):
        with self.cv:
            self.items[index] = oid
            self.cv.notify_all()

    def finish(self, count: int):
        with self.cv:
            if self.total is None or count < self.total:
                self.total = count
            self.cv.notify_all()


class StreamManager:
    """Owner-side registry of live streams (one per streaming task)."""

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._streams: dict[TaskID, _Stream] = {}
        # streams dropped by their consumer before draining: producers are
        # told to cancel on their next report/poll
        # insertion-ordered so the size bound evicts the OLDEST entry — an
        # arbitrary eviction could drop a producer that has not yet polled,
        # leaving it running the generator to completion for nobody
        self._abandoned: dict[TaskID, None] = {}

    def register(self, spec: "TaskSpec") -> "ObjectRefGenerator":
        st = _Stream(spec.task_id)
        with self._lock:
            self._streams[spec.task_id] = st
        return ObjectRefGenerator(st, self._rt, spec.owner_id,
                                  spec.owner_addr)

    def get(self, task_id: TaskID) -> _Stream | None:
        with self._lock:
            return self._streams.get(task_id)

    def discard(self, task_id: TaskID):
        with self._lock:
            self._streams.pop(task_id, None)

    def on_item(self, body: dict) -> dict:
        """Owner-side handler for executor item reports
        (ReportGeneratorItemReturns analog). The reply carries the
        consumer's cursor so the executor can throttle to consumption, not
        just delivery.

        Works with NO live stream too: lineage reconstruction re-runs the
        generator after the consumer finished iterating — replayed items
        whose refs are still held must be re-stored even though the stream
        itself is gone."""
        from ray_tpu.core.serialization import SerializedObject

        tid = body["task_id"]
        with self._lock:
            abandoned = tid in self._abandoned
            if abandoned and body.get("done"):
                self._abandoned.pop(tid, None)  # producer wound down
        if abandoned:
            return {"ok": True, "cancel": True}
        pending = self._rt.task_manager.get_pending_spec(tid)
        if pending is None or body.get("attempt", 0) != pending.attempt_number:
            return {"ok": True, "stale": True}
        st = self.get(tid)
        if body.get("done"):
            if st is not None:
                st.finish(body["count"])
            return {"ok": True, "consumed": self._consumed(st)}
        oid, kind, data, is_error = body["item"]
        already_consumed = (st is not None
                            and body["index"] < self._consumed(st))
        if (st is None or already_consumed) \
                and self._rt.reference_counter.owned_count(oid) <= 0:
            # nobody holds (or will ever get) this item's ref — a retry
            # replaying consumed indices, or a stream that's gone; storing
            # it would pin it forever
            return {"ok": True, "consumed": self._consumed(st)}
        if kind == "inline":
            self._rt.memory_store.put_inline(
                oid, SerializedObject.from_buffer(data), is_error)
        else:
            self._rt.memory_store.put_location(oid, data)
            # lineage: a lost shm item is reconstructed by re-running the
            # whole generator (deterministic ids make the replay line up)
            self._rt.task_manager.add_stream_lineage(oid, pending)
        if st is not None and not already_consumed:
            self._rt.reference_counter.add_owned(oid)
            st.put(body["index"], oid)
            if self.get(tid) is None:
                # abandon() raced this report after our stream lookup;
                # its cleanup missed this item — drop it ourselves
                self._rt.reference_counter.drop_if_unreferenced(oid)
        return {"ok": True, "consumed": self._consumed(st)}

    def _consumed(self, st: _Stream | None) -> int:
        """Consumer progress for executor backpressure; an absent (finished
        or abandoned) stream reports 'everything consumed' so the producer
        never blocks on a consumer that will not come back."""
        if st is None:
            return 1 << 62
        with st.cv:
            return st.cursor

    def on_consumed_query(self, body: dict) -> dict:
        """Executor poll while backpressure-blocked (the consumer advancing
        its cursor does not otherwise reach the executor)."""
        tid = body["task_id"]
        with self._lock:
            if tid in self._abandoned:
                return {"cancel": True}
        return {"consumed": self._consumed(self.get(tid))}

    def abandon(self, task_id: TaskID):
        """Consumer dropped the generator before draining it: forget the
        stream, free buffered items nobody will ever pop (their refs were
        never handed out, so no dec event would ever fire), and tell the
        producer to stop on its next report/poll."""
        st = self.get(task_id)
        if st is None:
            return
        with self._lock:
            self._abandoned[task_id] = None
            if len(self._abandoned) > 4096:  # bound: ids of dead producers
                self._abandoned.pop(next(iter(self._abandoned)))
        self.discard(task_id)
        with st.cv:
            pending_items = list(st.items.values())
            st.items.clear()
            st.total = st.cursor  # unblock any concurrent next()
            st.cv.notify_all()
        for oid in pending_items:
            self._rt.reference_counter.drop_if_unreferenced(oid)

    def fail(self, spec: "TaskSpec", error_sobj):
        """Terminal task failure: surface the error as the stream's next
        item so consumers unblock instead of hanging."""
        st = self.get(spec.task_id)
        if st is None:
            return
        with st.cv:
            idx = (max(st.items) + 1) if st.items else 0
            idx = max(idx, st.cursor)
            oid = ObjectID.for_return(spec.task_id, idx + 1)
            self._rt.memory_store.put_inline(oid, error_sobj, is_error=True)
            self._rt.reference_counter.add_owned(oid)
            st.items[idx] = oid
            st.total = idx + 1
            st.cv.notify_all()


class ObjectRefGenerator:
    """Iterator over a streaming task's item refs (reference:
    python/ray/_raylet ObjectRefGenerator). Each ``next()`` returns an
    ``ObjectRef`` as soon as the executor has reported that item; pass it to
    ``ray_tpu.get`` (or nested tasks) like any ref."""

    def __init__(self, stream: _Stream, runtime, owner_id, owner_addr):
        self._stream = stream
        self._rt = runtime
        self._owner_id = owner_id
        self._owner_addr = owner_addr

    def __del__(self):
        # abandoned before StopIteration: release buffered items (the
        # producer unblocks via the absent-stream consumed sentinel).
        # DEFERRED like ObjectRef.__del__ — abandon takes stream/refcount/
        # memory-store locks and a destructor can fire while this thread
        # holds them (GC-reentrancy; see object_ref.py).
        try:
            st = self._stream
            if st.total is None or st.cursor < st.total:
                mgr = self._rt.stream_manager
                self._rt.defer_call(lambda: mgr.abandon(st.task_id))
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def __iter__(self):
        return self

    def __next__(self):
        return self._next_ref(timeout=None)

    def next_ready(self):
        """Non-blocking: the next ref if already reported, else None."""
        try:
            return self._next_ref(timeout=0.0)
        except StopIteration:
            raise
        except Exception:
            return None

    def _next_ref(self, timeout: float | None):
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.exceptions import GetTimeoutError

        st = self._stream
        watchdog = timeout is None and get_config().blocking_watchdog_s > 0
        if watchdog:
            timeout = get_config().blocking_watchdog_s
        deadline = None if timeout is None else time.monotonic() + timeout
        with st.cv:
            while True:
                if st.cursor in st.items:
                    oid = st.items.pop(st.cursor)
                    st.cursor += 1
                    return ObjectRef(oid, self._owner_id, self._owner_addr)
                if st.total is not None and st.cursor >= st.total:
                    self._rt.stream_manager.discard(st.task_id)
                    raise StopIteration
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"stream next() timed out after {timeout:.0f}s"
                        + (" (blocking watchdog; pass an explicit timeout or "
                           "raise RAY_TPU_BLOCKING_WATCHDOG_S)"
                           if watchdog else ""))
                st.cv.wait(remaining if remaining is None
                           else min(remaining, 1.0))

    def completed_count(self) -> int:
        with self._stream.cv:
            return self._stream.cursor

    def is_finished(self) -> bool:
        st = self._stream
        with st.cv:
            return st.total is not None and st.cursor >= st.total
