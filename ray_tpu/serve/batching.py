"""@serve.batch: dynamic request batching.

TPU-native analog of the reference's batching
(/root/reference/python/ray/serve/batching.py — @serve.batch:535,
_BatchQueue:105): calls buffer until max_batch_size or batch_wait_timeout_s,
then the underlying fn runs once on the list of requests and each caller gets
its element back. On TPU replicas this is the host-side half of batching;
the device-side half (padding to bucketed static shapes for XLA) is the
engine's job (ray_tpu.serve.llm).
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional

from ray_tpu.core import deadline as request_deadline
from ray_tpu.exceptions import DeadlineExceededError


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._task = None

    def _ensure(self):
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._task = asyncio.ensure_future(self._loop())

    async def submit(self, item) -> Any:
        # admission: an already-expired request must not occupy a batch slot
        request_deadline.raise_if_expired("batched call")
        self._ensure()
        fut = asyncio.get_event_loop().create_future()
        await self._queue.put((item, fut))
        rem = request_deadline.remaining()
        if rem is None:
            return await fut
        try:
            # bound the wait by the REMAINING deadline; wait_for cancels the
            # future on timeout, and the batch loop skips done futures — the
            # expired caller's slot does no further work on its behalf
            return await asyncio.wait_for(fut, max(rem, 0.001))
        except asyncio.TimeoutError:
            raise DeadlineExceededError(
                "batched call deadline exceeded waiting for batch result")

    async def _loop(self):
        while True:
            item, fut = await self._queue.get()
            batch = [(item, fut)]
            deadline = asyncio.get_event_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), remaining))
                except asyncio.TimeoutError:
                    break
            items = [b[0] for b in batch]
            try:
                results = self._fn(*_split_self(items))
                if asyncio.iscoroutine(results):
                    results = await results
                if len(results) != len(items):
                    raise RuntimeError(
                        f"batched fn returned {len(results)} results for "
                        f"{len(items)} inputs")
                for (_, f), r in zip(batch, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:  # noqa: BLE001 - propagate to callers
                for _, f in batch:
                    if not f.done():
                        f.set_exception(e)


def _split_self(items: list):
    """items are (maybe (marker, self, arg)) tuples from the wrapper."""
    if items and isinstance(items[0], tuple) and len(items[0]) == 3 \
            and items[0][0] == _METHOD:
        self_obj = items[0][1]
        return (self_obj, [it[2] for it in items])
    return ([it for it in items],)


# String marker, not `object()`: the wrapper closure travels through
# cloudpickle into replica workers, and a pickled object() loses identity.
_METHOD = "__serve_batch_method_marker__"


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for batched endpoints (reference @serve.batch:535).

    The wrapped fn must accept a list and return a list of equal length.
    Works on free functions and methods.
    """

    def decorate(fn):
        queues: dict[int, _BatchQueue] = {}

        def get_queue(key: int) -> _BatchQueue:
            q = queues.get(key)
            if q is None:
                q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                queues[key] = q
            return q

        import inspect
        params = list(inspect.signature(fn).parameters)
        is_method = params and params[0] == "self"

        if is_method:
            @functools.wraps(fn)
            async def method_wrapper(self, item):
                return await get_queue(id(self)).submit((_METHOD, self, item))
            method_wrapper._is_serve_batch = True
            return method_wrapper

        @functools.wraps(fn)
        async def fn_wrapper(item):
            return await get_queue(0).submit(item)
        fn_wrapper._is_serve_batch = True
        return fn_wrapper

    if _fn is not None:
        return decorate(_fn)
    return decorate
