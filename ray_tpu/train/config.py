"""Train configuration types.

TPU-native analog of the reference's Train v2 config surface
(/root/reference/python/ray/train/v2/api/config.py — ScalingConfig with
use_tpu:89 / topology:90, validation :96-138; RunConfig; FailureConfig) and
the checkpoint config (python/ray/train/_checkpoint.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one needs.

    TPU-first: `use_tpu` + `topology` select a slice (gang-scheduled via an
    atomic slice placement group); `num_workers` is hosts in the slice.
    """

    num_workers: int = 1
    use_tpu: bool = False
    topology: Optional[str] = None          # e.g. "4x4" / "2x2x2"
    accelerator_type: Optional[str] = None  # e.g. "v5p", "v6e"
    resources_per_worker: Optional[dict] = None
    placement_strategy: str = "PACK"        # SPREAD for one-worker-per-host TPU

    def __post_init__(self):
        if self.num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if self.topology and not self.use_tpu:
            raise ValueError("topology requires use_tpu=True")
        if self.use_tpu and self.placement_strategy == "PACK":
            # One worker process per TPU host is the only supported layout
            # (SURVEY.md §7 hard part 7: single process per chipset).
            self.placement_strategy = "SPREAD"

    @property
    def _resources_per_worker(self) -> dict:
        if self.resources_per_worker:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            return {"TPU": 4}
        return {"CPU": 1}

    def total_resources(self) -> dict:
        per = self._resources_per_worker
        return {k: v * self.num_workers for k, v in per.items()}


@dataclasses.dataclass
class FailureConfig:
    """Retry budget for worker-group failures.

    Mirrors reference FailureConfig semantics
    (train/v2/_internal/execution/failure_handling/failure_policy.py):
    max_failures=-1 retries forever; 0 fails fast.
    """

    max_failures: int = 0
    fail_fast: bool = False


@dataclasses.dataclass
class CheckpointConfig:
    """Top-K checkpoint retention (reference: train/v2 checkpoint manager,
    checkpoint_manager.py:78)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    """Where run outputs (checkpoints, results) land."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    callbacks: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.environ.get(
                "RAY_TPU_STORAGE_PATH",
                os.path.join(os.path.expanduser("~"), "ray_tpu_results"))


@dataclasses.dataclass
class Result:
    """Terminal state of a training run (reference: train/v2/api/result.py)."""

    metrics: Optional[dict] = None
    checkpoint: Optional[Any] = None
    error: Optional[BaseException] = None
    path: Optional[str] = None
    best_checkpoints: list = dataclasses.field(default_factory=list)

    @property
    def metrics_dataframe(self):
        raise NotImplementedError(
            "metrics_dataframe requires pandas history tracking; "
            "use Result.metrics")
