"""Tune tests (models reference python/ray/tune/tests/: variant generation,
schedulers, end-to-end Tuner.fit, PBT mutation, Train integration)."""

import os

import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu import tune
from ray_tpu.train import RunConfig


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module



def _run_cfg(tmp_path):
    return RunConfig(storage_path=str(tmp_path))


def test_variant_generation_grid_and_random():
    from ray_tpu.tune.search import BasicVariantGenerator

    space = {"lr": tune.grid_search([0.1, 0.01]),
             "wd": tune.choice([1, 2, 3]),
             "fixed": 7}
    variants = BasicVariantGenerator(space, num_samples=2, seed=0).variants()
    assert len(variants) == 4  # 2 grid x 2 samples
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert all(v["fixed"] == 7 for v in variants)
    assert all(v["wd"] in (1, 2, 3) for v in variants)


def test_variant_nested_space():
    from ray_tpu.tune.search import BasicVariantGenerator

    space = {"opt": {"lr": tune.uniform(0.0, 1.0),
                     "sched": tune.grid_search(["cos", "lin"])}}
    variants = BasicVariantGenerator(space, seed=1).variants()
    assert len(variants) == 2
    assert all(0.0 <= v["opt"]["lr"] <= 1.0 for v in variants)


def test_tuner_fit_basic(ray_start_regular, tmp_path):
    def trainable(config):
        score = -(config["x"] - 3) ** 2
        tune.report({"score": score})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=3),
        run_config=_run_cfg(tmp_path))
    grid = tuner.fit()
    assert len(grid) == 5
    assert not grid.errors
    best = grid.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0


def test_tuner_trial_error_isolated(ray_start_regular, tmp_path):
    def trainable(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"score": config["x"]})

    grid = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=_run_cfg(tmp_path)).fit()
    assert len(grid.errors) == 1
    assert grid.get_best_result().config["x"] == 2


def test_asha_stops_bad_trials(ray_start_regular, tmp_path):
    def trainable(config):
        for step in range(20):
            tune.report({"acc": config["quality"] * (step + 1) / 20.0,
                         "training_iteration": step + 1})

    sched = tune.ASHAScheduler(metric="acc", mode="max", max_t=20,
                               grace_period=2, reduction_factor=2)
    grid = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search([0.1, 0.2, 0.9, 1.0])},
        tune_config=tune.TuneConfig(metric="acc", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=4),
        run_config=_run_cfg(tmp_path)).fit()
    best = grid.get_best_result()
    assert best.config["quality"] in (0.9, 1.0)
    # at least one weak trial should have been stopped before max_t
    iters = [len(r.history) for r in grid]
    assert min(iters) < 20


def test_scheduler_asha_unit():
    from ray_tpu.tune.schedulers import CONTINUE, STOP, ASHAScheduler
    from ray_tpu.tune.tuner import Trial

    sched = ASHAScheduler(metric="m", mode="max", max_t=8, grace_period=2,
                          reduction_factor=2)
    good = Trial("good", {})
    bad = Trial("bad", {})
    out = []
    for t in (1, 2):
        out.append(sched.on_result(good, {"m": 1.0, "training_iteration": t}))
    # bad trial hits rung 2 with much worse metric after good recorded
    sched.on_result(bad, {"m": 1.0, "training_iteration": 1})
    decision = sched.on_result(bad, {"m": 0.01, "training_iteration": 2})
    assert decision == STOP
    assert sched.on_result(good, {"m": 1.0, "training_iteration": 8}) == STOP


def test_pbt_mutation_unit():
    sched = tune.PopulationBasedTraining(
        metric="m", mode="max", perturbation_interval=1,
        hyperparam_mutations={"lr": [0.1, 0.2, 0.4]}, seed=0)
    cfg = sched.mutate_config({"lr": 0.1, "other": 5})
    assert cfg["lr"] in (0.1, 0.2, 0.4)
    assert cfg["other"] == 5


def test_tuner_with_checkpoints(ray_start_regular, tmp_path):
    def trainable(config):
        import tempfile

        d = tempfile.mkdtemp()
        with open(os.path.join(d, "w.txt"), "w") as f:
            f.write(str(config["x"]))
        tune.report({"score": config["x"]},
                    checkpoint=rt_train.Checkpoint(d))

    grid = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=_run_cfg(tmp_path)).fit()
    best = grid.get_best_result()
    assert best.checkpoint is not None
    assert open(os.path.join(best.checkpoint.path, "w.txt")).read() == "2"


def test_tuner_over_trainer(ray_start_regular, tmp_path):
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def train_fn(config):
        rt_train.report({"loss": abs(config.get("lr", 1.0) - 0.1)})

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "inner")))
    grid = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {
            "lr": tune.grid_search([0.1, 0.5])}},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    max_concurrent_trials=1),
        run_config=_run_cfg(tmp_path)).fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["loss"] == pytest.approx(0.0)


def test_tpe_searcher_converges(ray_start_regular, tmp_path):
    """The native TPE searcher beats random in expectation on a smooth 1-d
    objective: later suggestions cluster near the optimum."""

    def trainable(config):
        x = config["x"]
        tune.report({"score": -(x - 3.0) ** 2,
                     "training_iteration": 1})

    searcher = tune.TPESearcher(
        {"x": tune.uniform(-10.0, 10.0)}, metric="score", mode="max",
        n_initial=6, seed=0)
    grid = tune.Tuner(
        trainable, param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=24, search_alg=searcher,
                                    max_concurrent_trials=4),
        run_config=_run_cfg(tmp_path)).fit()
    results = [r for r in grid if r.metrics]
    assert len(results) == 24
    best = grid.get_best_result()
    assert abs(best.config["x"] - 3.0) < 1.5, best.config
    # the model-guided half should be closer to the optimum on average
    first = [abs(r.config["x"] - 3.0) for r in results[:8]]
    last = [abs(r.config["x"] - 3.0) for r in results[-8:]]
    assert sum(last) / 8 < sum(first) / 8


def test_tpe_searcher_unit():
    from ray_tpu.tune.search import TPESearcher, choice, loguniform

    s = TPESearcher({"lr": loguniform(1e-5, 1e-1), "opt": choice(["a", "b"])},
                    metric="m", mode="max", n_initial=4, seed=1)
    # seed observations: lr near 1e-3 with opt=a is best
    for i in range(12):
        cfg = s.suggest(f"t{i}")
        lr, opt = cfg["lr"], cfg["opt"]
        score = -abs(__import__("math").log10(lr) + 3.0) + (0.5 if opt == "a" else 0.0)
        s.on_trial_complete(f"t{i}", {"m": score})
    # guided suggestions should prefer opt=a and lr near 1e-3
    picks = [s.suggest(f"g{i}") for i in range(10)]
    for i, _ in enumerate(picks):
        s.on_trial_complete(f"g{i}", None, error=True)
    a_frac = sum(1 for p in picks if p["opt"] == "a") / len(picks)
    assert a_frac >= 0.6


def test_tpe_beats_random_on_fixed_budget():
    """Validation for the in-tree TPE (VERDICT r3/r4): on a smooth
    2-D objective with a fixed trial budget, TPE's best-found must beat
    random search's across seed-paired runs (reference: the optuna/
    hyperopt integrations are validated the same way)."""
    from ray_tpu.tune.search import RandomSearcher, TPESearcher, uniform

    def objective(cfg):
        # unimodal bowl with optimum at (0.3, -0.7); best value 0
        return -((cfg["x"] - 0.3) ** 2 + (cfg["y"] + 0.7) ** 2)

    space = {"x": uniform(-2, 2), "y": uniform(-2, 2)}
    budget = 40
    tpe_wins = 0
    for seed in range(5):
        best = {}
        for name, searcher in (
                ("tpe", TPESearcher(space, metric="score", mode="max",
                                    n_initial=8, seed=seed)),
                ("rnd", RandomSearcher(space, seed=seed))):
            vals = []
            for i in range(budget):
                cfg = searcher.suggest(f"t{i}")
                score = objective(cfg)
                searcher.on_trial_complete(f"t{i}", {"score": score})
                vals.append(score)
            best[name] = max(vals)
        if best["tpe"] >= best["rnd"]:
            tpe_wins += 1
    assert tpe_wins >= 4, f"TPE won only {tpe_wins}/5 paired runs"


def test_optuna_adapter_gates_cleanly():
    """optuna is optional; without it the adapter must raise a clear
    ImportError (and with it, drive a short study end-to-end)."""
    from ray_tpu.tune.search import OptunaSearch, uniform

    space = {"x": uniform(0, 1)}
    try:
        import optuna  # noqa: F401
        have_optuna = True
    except ImportError:
        have_optuna = False

    if not have_optuna:
        with pytest.raises(ImportError, match="optuna"):
            OptunaSearch(space, metric="score")
        return
    s = OptunaSearch(space, metric="score", mode="max", seed=0)
    for i in range(10):
        cfg = s.suggest(f"t{i}")
        s.on_trial_complete(f"t{i}", {"score": -(cfg["x"] - 0.5) ** 2})


def test_pb2_gp_explore_unit():
    """PB2's explore step proposes inside bounds and, with observations,
    prefers the direction the GP credits with score improvement."""
    from ray_tpu.tune.schedulers import PB2

    sched = PB2(metric="m", mode="max", perturbation_interval=1,
                hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0)
    # feed observations: higher lr -> bigger improvement
    for lr, dscore in [(0.1, 0.0), (0.3, 0.2), (0.5, 0.45),
                       (0.7, 0.72), (0.9, 0.95)]:
        sched._observations.append(({"lr": lr}, dscore))
    picks = [sched.mutate_config({"lr": 0.5})["lr"] for _ in range(8)]
    assert all(0.0 <= p <= 1.0 for p in picks)
    # the GP-UCB should push above the base more often than below
    assert sum(p > 0.5 for p in picks) >= 5, picks


def test_bohb_pair_drives_tuner(ray_start_regular, tmp_path):
    """create_bohb wires the TPE-per-rung searcher to the bracket
    scheduler; a short tuning run completes and finds a good x."""
    from ray_tpu.tune.search import create_bohb

    def trainable(config, report=None):
        for step in range(1, 5):
            tune.report({"score": -(config["x"] - 0.6) ** 2 * step,
                         "training_iteration": step})

    space = {"x": tune.uniform(0, 1)}
    searcher, scheduler = create_bohb(
        space, metric="score", mode="max", max_t=4, grace_period=1)
    result = tune.Tuner(
        trainable, param_space=space,
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=12, search_alg=searcher,
                                    scheduler=scheduler),
        run_config=_run_cfg(tmp_path)).fit()
    best = result.get_best_result()
    assert abs(best.config["x"] - 0.6) < 0.35, best.config
    # rung observations reached the searcher
    assert searcher._rungs, "scheduler never fed the searcher"
