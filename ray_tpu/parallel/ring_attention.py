"""Ring attention: context-parallel exact attention over the ICI ring.

The reference ships NO sequence/context parallelism (SURVEY.md §5.7 — verified
absent; long context is delegated to vLLM/user code). Per the parity
requirement this framework implements it natively: the sequence is sharded over
the mesh "context" axis; each device holds a Q/K/V shard and K/V blocks rotate
around the ring with `ppermute` while a streaming-softmax accumulator builds
exact attention (blockwise attention à la Ring Attention, Liu et al.).

The per-block kernel is `ray_tpu.ops.attention.block_attention` — a Pallas
flash kernel on TPU, einsum fallback elsewhere — so the MXU does the FLOPs and
the ICI rotation overlaps with compute (XLA schedules the ppermute
asynchronously against the next block's matmuls).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import shard_map_compat as shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attn(q, k, v, *, q_offset, k_offset, causal, sm_scale):
    """One (q-shard × kv-block) attention contribution with streaming-softmax
    stats. Shapes: q [B,Tq,H,D], k/v [B,Tk,H,D]. Returns (out, m, l)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale  # [B,H,Tq,Tk]
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)  # [B,H,Tq]
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)  # [B,Tq,H,D]
    return out, m_safe, l, jnp.isfinite(m)


def _merge(acc, new):
    """Streaming-softmax merge of two partial attention results."""
    o1, m1, l1, any1 = acc
    o2, m2, l2, any2 = new
    m = jnp.maximum(jnp.where(any1, m1, -jnp.inf), jnp.where(any2, m2, -jnp.inf))
    m_safe = jnp.where(any1 | any2, m, 0.0)
    c1 = jnp.where(any1, jnp.exp(m1 - m_safe), 0.0)
    c2 = jnp.where(any2, jnp.exp(m2 - m_safe), 0.0)
    l = l1 * c1 + l2 * c2
    o = o1 * c1.transpose(0, 2, 1)[..., None] + o2 * c2.transpose(0, 2, 1)[..., None]
    return o, m_safe, l, any1 | any2


def _ring_attention_sharded(q, k, v, *, axis_name: str, causal: bool,
                            sm_scale: float, block_fn: Callable):
    """Runs inside shard_map: q/k/v are the local sequence shards."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]  # kv rotates to the next device

    o0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full(q.shape[:1] + (q.shape[2], q.shape[1]), 0.0, jnp.float32)  # [B,H,Tq]
    l0 = jnp.zeros_like(m0)
    any0 = jnp.zeros(m0.shape, bool)

    def step(i, carry):
        acc, kv = carry
        k_blk, v_blk = kv
        src = (idx - i) % n  # whose kv block we currently hold
        new = block_fn(q, k_blk, v_blk,
                       q_offset=idx * t_local, k_offset=src * t_local,
                       causal=causal, sm_scale=sm_scale)
        acc = _merge(acc, new)
        kv = jax.lax.ppermute((k_blk, v_blk), axis_name, perm)
        return acc, kv

    (o, m, l, anyv), _ = jax.lax.fori_loop(
        0, n, step, ((o0, m0, l0, any0), (k, v)))
    l_safe = jnp.where(l > 0, l, 1.0)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "context",
                   causal: bool = True, sm_scale: float | None = None,
                   block_fn: Callable | None = None):
    """Exact attention with the sequence sharded over ``axis_name``.

    q/k/v: [batch, seq, heads, head_dim], seq sharded over the context axis.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if block_fn is None:
        block_fn = _block_attn
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        out, m, l, anyv = _block_attn(q, k, v, q_offset=0, k_offset=0,
                                      causal=causal, sm_scale=sm_scale)
        l_safe = jnp.where(l > 0, l, 1.0)
        return (out / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    seq_spec = P(None, axis_name, None, None)
    fn = functools.partial(_ring_attention_sharded, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale, block_fn=block_fn)
    return shard_map(
        fn, mesh=mesh, in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec, check=False)(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis_name: str = "context",
                      causal: bool = True, sm_scale: float | None = None,
                      attn_fn: Callable | None = None):
    """Ulysses/DeepSpeed-style sequence parallelism: all-to-all re-shards
    sequence ↔ heads so each device runs full-sequence attention on a head
    subset, then re-shards back (SURVEY.md §5.7 alternative form). Requires
    heads % context_size == 0."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5

    def full_attn(q, k, v):
        out, m, l, anyv = _block_attn(q, k, v, q_offset=0, k_offset=0,
                                      causal=causal, sm_scale=sm_scale)
        l_safe = jnp.where(l > 0, l, 1.0)
        return (out / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)

    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        return full_attn(q, k, v)

    if attn_fn is None:
        attn_fn = full_attn

    def inner(q, k, v):
        # [B, T/n, H, D] --a2a--> [B, T, H/n, D]
        def seq_to_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                      tiled=True)

        out = attn_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
        return heads_to_seq(out)

    seq_spec = P(None, axis_name, None, None)
    return shard_map(inner, mesh=mesh, in_specs=(seq_spec,) * 3,
                         out_specs=seq_spec, check=False)(q, k, v)
