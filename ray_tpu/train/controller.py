"""Train controller: the run state machine.

TPU-native analog of the reference's TrainController
(/root/reference/python/ray/train/v2/_internal/execution/controller/
controller.py:96 — states Initializing/Scheduling/Running/Restarting/
Resizing/Finished/Errored in state.py, loop run:480/_step:386), with the
failure policy (failure_handling/failure_policy.py) and scaling policy
(scaling_policy/fixed.py) folded in. Elasticity on TPU is restart-the-world:
JAX's distributed runtime can't resize in place, so every recovery goes
through Restarting with Orbax/dir checkpoint resume (SURVEY.md §7 hard
part 4).
"""

from __future__ import annotations

import enum
import logging
import time
from typing import Callable, Optional

from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    StorageContext,
    new_run_name,
)
from ray_tpu.train.config import FailureConfig, Result, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class RunState(enum.Enum):
    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    RESIZING = "RESIZING"
    ERRORED = "ERRORED"
    FINISHED = "FINISHED"


class FailureDecision(enum.Enum):
    RETRY = "RETRY"
    RAISE = "RAISE"


class FailurePolicy:
    """max_failures budget → retry or raise (reference default.py)."""

    def __init__(self, failure_config: FailureConfig):
        self._cfg = failure_config
        self._failures = 0

    def make_decision(self, error: str) -> FailureDecision:
        self._failures += 1
        if self._cfg.fail_fast:
            return FailureDecision.RAISE
        if self._cfg.max_failures < 0:
            return FailureDecision.RETRY
        if self._failures <= self._cfg.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.RAISE


class TrainController:
    """Drives one training run to completion.

    Runs in the driver process (the reference runs it as a detached actor;
    here the Tuner/driver owns it directly — the worker gang is still fully
    remote, so controller placement is an orchestration detail).
    """

    def __init__(self, train_fn: Callable, *, train_fn_config: Optional[dict],
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 datasets: Optional[dict] = None,
                 backend_fn: Optional[Callable] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 scaling_policy=None,
                 poll_interval_s: float = 0.05):
        from ray_tpu.train.scaling import FixedScalingPolicy

        self._train_fn = train_fn
        self._train_fn_config = train_fn_config
        self._scaling = scaling_config
        self._run_config = run_config
        self._datasets = datasets or {}
        self._backend_fn = backend_fn
        self._scaling_policy = scaling_policy or FixedScalingPolicy()
        self._num_workers = scaling_config.num_workers
        self._poll_interval_s = poll_interval_s

        self._run_name = run_config.name or new_run_name()
        self._storage = StorageContext(run_config.storage_path, self._run_name)
        ckpt_cfg = run_config.checkpoint_config
        self._ckpt_manager = CheckpointManager(
            self._storage, num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order)
        self._failure_policy = FailurePolicy(run_config.failure_config)

        self.state = RunState.INITIALIZING
        self._worker_group: Optional[WorkerGroup] = None
        self._latest_metrics: Optional[dict] = None
        self._resume_checkpoint = resume_from_checkpoint
        self._error: Optional[str] = None
        # seq -> {rank: report}; a step's checkpoints may arrive across
        # several polls — only register once the gang's reports are complete
        self._pending_reports: dict[int, dict[int, object]] = {}

    # -- state transitions -------------------------------------------------
    def _start_worker_group(self):
        self.state = RunState.SCHEDULING
        self._num_workers = \
            self._scaling_policy.make_decision_for_non_running_worker_group(
                self._num_workers)
        import dataclasses as _dc
        scaling = _dc.replace(self._scaling, num_workers=self._num_workers) \
            if self._num_workers != self._scaling.num_workers \
            else self._scaling
        wg = WorkerGroup(scaling, experiment_name=self._run_name,
                         trial_dir=self._storage.run_path)
        shards = self._split_datasets(self._num_workers)
        resume = self._resume_checkpoint
        if self._ckpt_manager.latest is not None:
            resume = self._ckpt_manager.latest.checkpoint
        wg.start(hparams=self._train_fn_config,
                 dataset_shards_per_rank=shards,
                 resume_checkpoint=resume,
                 backend_fn=self._backend_fn)
        wg.run_train_fn(self._train_fn, self._train_fn_config)
        self._worker_group = wg
        self.state = RunState.RUNNING

    def _split_datasets(self, n: int) -> Optional[list[dict]]:
        if not self._datasets:
            return None
        per_rank: list[dict] = [dict() for _ in range(n)]
        for name, ds in self._datasets.items():
            splits = _maybe_streaming_split(ds, n)
            for rank in range(n):
                per_rank[rank][name] = splits[rank]
        return per_rank

    def _handle_reports(self, statuses) -> None:
        """Collect per-rank reports; persist checkpoints. A step's reports
        can straggle across polls, so they buffer in _pending_reports until
        every rank has reported that seq (reference: the SynchronizationActor
        barrier makes report a collective)."""
        world = len(statuses)
        for rank, st in enumerate(statuses):
            if st is None:
                continue
            for rep in st.reports:
                self._pending_reports.setdefault(rep.seq, {})[rank] = rep
        for seq in sorted(self._pending_reports):
            if len(self._pending_reports[seq]) < world:
                continue
            self._process_seq(seq, self._pending_reports.pop(seq), world)

    def _flush_pending_reports(self, world: int) -> None:
        """Register whatever arrived for incomplete steps (gang finished,
        failed, or is being resized)."""
        for seq in sorted(self._pending_reports):
            self._process_seq(seq, self._pending_reports.pop(seq), world)

    def _process_seq(self, seq: int, group: dict, world: int) -> None:
        ranked = sorted(group.items())
        metrics = ranked[0][1].metrics
        self._latest_metrics = metrics
        with_ckpt = [(rank, rep.checkpoint) for rank, rep in ranked
                     if rep.checkpoint is not None]
        sharded = [rc for rc in with_ckpt
                   if rc[1].get_metadata().get("shard")]
        if sharded:
            # distributed checkpoint (EXPLICIT opt-in: each rank marked its
            # payload with metadata {"shard": True}): merge the per-rank
            # shards (Orbax-style per-host writes, SURVEY.md §5.4) into one
            # dir: shard-{rank:05d}/... . A PARTIAL shard set (a resize or
            # failure flushed an incomplete step) is unusable for restore —
            # registering it as-is would hand the resumed gang a raw
            # unmerged shard — so it is dropped, not promoted.
            if len(sharded) == world:
                self._ckpt_manager.register_sharded(
                    sharded, metrics, world_size=world)
                self._ckpt_manager.write_state()
        elif with_ckpt:
            # default: rank 0's (full) checkpoint wins — reference
            # report_handler semantics
            self._ckpt_manager.register(with_ckpt[0][1], metrics)
            self._ckpt_manager.write_state()

    def _teardown_workers(self):
        if self._worker_group is not None:
            self._worker_group.shutdown()
            self._worker_group = None

    # -- main loop ---------------------------------------------------------
    def run(self) -> Result:
        last_pub = 0.0
        while self.state not in (RunState.FINISHED, RunState.ERRORED):
            self._step()
            now = time.monotonic()
            if now - last_pub >= 1.0:
                self._publish_run_state()
                last_pub = now
        latest = self._ckpt_manager.latest
        best = self._ckpt_manager.best_checkpoints()
        err = None
        if self.state == RunState.ERRORED:
            err = TrainingFailedError(self._error or "training failed")
        self._publish_run_state()
        return Result(
            metrics=self._latest_metrics,
            checkpoint=latest.checkpoint if latest else None,
            error=err, path=self._storage.run_path,
            best_checkpoints=best)

    def _publish_run_state(self) -> None:
        """Export the run's controller state to the CP KV for the dashboard
        (reference: train/v2/_internal/state + dashboard/modules/train/ —
        run/attempt state visible in the UI). Best-effort: a dashboardless
        cluster must not pay for failures here."""
        try:
            import json as _json

            from ray_tpu.core import api as _api
            rt = _api._try_get_runtime()
            if rt is None:
                return
            wg = self._worker_group
            workers = []
            if wg is not None:
                for w in getattr(wg, "workers", []) or []:
                    aid = getattr(w.actor, "_actor_id", None)
                    workers.append({
                        "rank": w.world_rank,
                        "node_id": w.node_id,
                        "actor_id": aid.hex()[:16] if aid is not None
                        else None,
                    })
            latest = self._ckpt_manager.latest
            payload = {
                "name": self._run_name,
                "state": self.state.value,
                "num_workers": self._num_workers,
                "workers": workers,
                "latest_metrics": self._latest_metrics,
                "error": self._error,
                "checkpoints": len(self._ckpt_manager.best_checkpoints()),
                "latest_checkpoint":
                    getattr(latest.checkpoint, "path", None)
                    if latest else None,
                "path": self._storage.run_path,
                "updated_at": time.time(),
            }
            # periodic run-state publish for the dashboard; the next
            # step's publish supersedes a lost one
            # graftlint: fire-and-forget
            rt.cp_client.notify("kv_put", {
                "key": f"train_run:{self._run_name}",
                "value": _json.dumps(payload, default=str).encode()})
        except Exception:  # noqa: BLE001 — observability must not fail runs
            pass

    def _step(self):
        if self.state in (RunState.INITIALIZING, RunState.RESTARTING,
                          RunState.RESIZING):
            try:
                self._start_worker_group()
            except Exception as e:  # noqa: BLE001 - scheduling failure
                self._on_failure(f"worker group start failed: {e!r}")
            return

        if self.state == RunState.RUNNING:
            statuses = self._worker_group.poll()
            self._handle_reports(statuses)
            dead = [i for i, s in enumerate(statuses) if s is None]
            errs = [(i, s.error) for i, s in enumerate(statuses)
                    if s is not None and s.error]
            if dead or errs:
                msg = "; ".join(
                    [f"rank {i} died" for i in dead] +
                    [f"rank {i}: {e.splitlines()[-1]}" for i, e in errs])
                full = "\n".join(e for _, e in errs) or msg
                self._on_failure(msg, full)
                return
            if all(s.finished for s in statuses):
                self._flush_pending_reports(len(statuses))
                self._teardown_workers()
                self.state = RunState.FINISHED
                return
            # elastic resize (restart-the-world; reference controller
            # Resizing state, scaling_policy.py ResizeDecision)
            from ray_tpu.train.scaling import ResizeDecision
            decision = \
                self._scaling_policy.make_decision_for_running_worker_group(
                    statuses, self._num_workers)
            if isinstance(decision, ResizeDecision) and \
                    decision.num_workers != self._num_workers:
                logger.info("resizing worker group %d -> %d (restart + "
                            "resume from latest checkpoint)",
                            self._num_workers, decision.num_workers)
                self._flush_pending_reports(len(statuses))
                self._teardown_workers()
                self._num_workers = decision.num_workers
                self.state = RunState.RESIZING
                return
            time.sleep(self._poll_interval_s)

    def _on_failure(self, msg: str, full: str = ""):
        logger.warning("training failure: %s", msg)
        self._flush_pending_reports(self._num_workers)
        self._teardown_workers()
        decision = self._failure_policy.make_decision(msg)
        if decision == FailureDecision.RETRY:
            logger.info("restarting worker group (resume from latest ckpt)")
            self.state = RunState.RESTARTING
        else:
            self._error = full or msg
            self.state = RunState.ERRORED


class TrainingFailedError(RuntimeError):
    pass


def _maybe_streaming_split(ds, n: int) -> list:
    """Split a Dataset into n per-rank iterators; pass lists/arrays through
    sliced."""
    split = getattr(ds, "streaming_split", None)
    if callable(split):
        return split(n, equal=True)
    if isinstance(ds, (list, tuple)):
        return [list(ds[i::n]) for i in range(n)]
    return [ds for _ in range(n)]
