// Native shared-memory object store: single mmapped arena + free-list
// allocator + LRU eviction.
//
// TPU-native analog of the reference's plasma store internals
// (/root/reference/src/ray/object_manager/plasma/store.cc,
//  plasma_allocator.cc + dlmalloc.cc, eviction_policy.cc): one POSIX shm
// arena per node agent; objects are [offset, size) extents handed out by a
// best-fit free list with coalescing; sealed+unpinned objects are evicted in
// LRU order when an allocation needs space. Clients (ray_tpu workers) mmap
// the arena once and read objects zero-copy at their offsets — the shm name
// plus offset plays the role of plasma's fd-passing (fling.cc).
//
// Exposed as a C ABI consumed via ctypes (ray_tpu/_native/__init__.py); the
// store object itself lives in the node-agent process only.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct Object {
  uint64_t offset = 0;
  uint64_t size = 0;
  bool sealed = false;
  bool pinned = true;
  uint64_t lru_tick = 0;
};

constexpr uint64_t kAlign = 64;  // cacheline; TPU host DMA likes >=64B

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

class ShmArenaStore {
 public:
  ShmArenaStore(const std::string& name, uint64_t capacity)
      : name_(name), capacity_(align_up(capacity)) {
    fd_ = shm_open(name_.c_str(), O_CREAT | O_RDWR | O_EXCL, 0600);
    if (fd_ < 0 && errno == EEXIST) {
      shm_unlink(name_.c_str());
      fd_ = shm_open(name_.c_str(), O_CREAT | O_RDWR | O_EXCL, 0600);
    }
    if (fd_ < 0) return;
    if (ftruncate(fd_, (off_t)capacity_) != 0) {
      close(fd_);
      fd_ = -1;
      return;
    }
    base_ = mmap(nullptr, capacity_, PROT_READ | PROT_WRITE, MAP_SHARED, fd_, 0);
    if (base_ == MAP_FAILED) {
      base_ = nullptr;
      close(fd_);
      fd_ = -1;
      return;
    }
    free_list_.push_back({0, capacity_});
    // Background page pre-toucher: writing each arena page once makes the
    // kernel allocate+zero it off the critical path. A cold put otherwise
    // pays a page fault + zeroing per 4 KiB inside its memcpy — measured
    // 1.1 GB/s cold vs 5.6 GB/s over pre-touched pages on the dev box
    // (tmpfs THP is 'never', so huge pages can't amortize the faults).
    // Chunks are memset under the allocator mutex and skip ranges already
    // handed out, so the toucher can never scribble over live object data.
    toucher_ = std::thread([this] { TouchLoop(); });
  }

  ~ShmArenaStore() {
    stop_.store(true);
    if (toucher_.joinable()) toucher_.join();
    // leak_mapping: in-process writers may still hold views into the
    // arena (a put mid-memcpy when another thread shuts down); the OS
    // reclaims at process exit — same lifetime model as the Python
    // client's _MappedSegment.close on still-exported views
    if (base_ != nullptr && !leak_mapping_.load()) munmap(base_, capacity_);
    if (fd_ >= 0) {
      close(fd_);
      shm_unlink(name_.c_str());
    }
  }

  void LeakMapping() { leak_mapping_.store(true); }

  bool ok() const { return base_ != nullptr; }

  // Allocate an extent for `id`. Evicts LRU unpinned sealed objects as
  // needed. Returns 0 on success (offset in *offset_out), -1 if the object
  // exists already (offset returned too), -2 if out of memory even after
  // eviction. Evicted ids are appended newline-separated into evicted_buf
  // (on BOTH the success and -2 paths — victims are deleted either way, so
  // owners must always be notified). Truncation keeps whole lines only.
  int Put(const std::string& id, uint64_t size, uint64_t* offset_out,
          char* evicted_buf, uint64_t evicted_cap) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it != objects_.end()) {
      *offset_out = it->second.offset;
      return -1;
    }
    uint64_t need = align_up(size == 0 ? kAlign : size);
    std::string evicted;
    int rc;
    while (true) {
      int64_t off = AllocLocked(need);
      if (off >= 0) {
        Object obj;
        obj.offset = (uint64_t)off;
        obj.size = size;
        obj.lru_tick = ++tick_;
        objects_[id] = obj;
        used_ += need;
        *offset_out = obj.offset;
        rc = 0;
        break;
      }
      // evict one LRU victim (sealed + unpinned)
      std::string victim;
      uint64_t best_tick = UINT64_MAX;
      for (const auto& kv : objects_) {
        if (kv.second.sealed && !kv.second.pinned &&
            kv.second.lru_tick < best_tick) {
          best_tick = kv.second.lru_tick;
          victim = kv.first;
        }
      }
      if (victim.empty()) {
        rc = -2;
        break;
      }
      evicted += victim;
      evicted += '\n';
      num_evicted_++;
      DeleteLocked(victim);
    }
    if (!evicted.empty() && evicted_buf != nullptr && evicted_cap > 0) {
      size_t n = evicted.size() < evicted_cap - 1 ? evicted.size()
                                                  : evicted_cap - 1;
      // never cut an id in half: drop back to the last complete line
      while (n > 0 && evicted[n - 1] != '\n') --n;
      memcpy(evicted_buf, evicted.data(), n);
      evicted_buf[n] = '\0';
    }
    return rc;
  }

  int Seal(const std::string& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    it->second.sealed = true;
    it->second.lru_tick = ++tick_;
    return 0;
  }

  int Get(const std::string& id, uint64_t* offset, uint64_t* size,
          int* sealed) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    it->second.lru_tick = ++tick_;
    *offset = it->second.offset;
    *size = it->second.size;
    *sealed = it->second.sealed ? 1 : 0;
    return 0;
  }

  int Pin(const std::string& id, int pinned) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    it->second.pinned = pinned != 0;
    return 0;
  }

  int Delete(const std::string& id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return -1;
    DeleteLocked(id);
    return 0;
  }

  void Stats(uint64_t* used, uint64_t* num_objects, uint64_t* num_evicted,
             uint64_t* capacity) {
    std::lock_guard<std::mutex> g(mu_);
    *used = used_;
    *num_objects = objects_.size();
    *num_evicted = num_evicted_;
    *capacity = capacity_;
  }

  void* base() const { return base_; }

 private:
  // best-fit with address-ordered free list + coalescing
  int64_t AllocLocked(uint64_t need) {
    auto best = free_list_.end();
    for (auto it = free_list_.begin(); it != free_list_.end(); ++it) {
      if (it->size >= need &&
          (best == free_list_.end() || it->size < best->size)) {
        best = it;
      }
    }
    if (best == free_list_.end()) return -1;
    uint64_t off = best->offset;
    if (best->size == need) {
      free_list_.erase(best);
    } else {
      best->offset += need;
      best->size -= need;
    }
    extents_[off] = need;
    return (int64_t)off;
  }

  void FreeLocked(uint64_t offset) {
    auto ext = extents_.find(offset);
    if (ext == extents_.end()) return;
    uint64_t size = ext->second;
    extents_.erase(ext);
    used_ -= size;
    // insert address-ordered, coalesce neighbors
    auto it = free_list_.begin();
    while (it != free_list_.end() && it->offset < offset) ++it;
    it = free_list_.insert(it, {offset, size});
    if (it != free_list_.begin()) {
      auto prev = std::prev(it);
      if (prev->offset + prev->size == it->offset) {
        prev->size += it->size;
        free_list_.erase(it);
        it = prev;
      }
    }
    auto next = std::next(it);
    if (next != free_list_.end() && it->offset + it->size == next->offset) {
      it->size += next->size;
      free_list_.erase(next);
    }
  }

  void DeleteLocked(const std::string& id) {
    auto it = objects_.find(id);
    if (it == objects_.end()) return;
    FreeLocked(it->second.offset);
    objects_.erase(it);
  }

  void TouchLoop() {
    constexpr uint64_t kChunk = 4ull << 20;  // ~0.7 ms memset per lock hold
    uint64_t frontier = 0;
    while (!stop_.load(std::memory_order_relaxed) && frontier < capacity_) {
      {
        std::lock_guard<std::mutex> g(mu_);
        uint64_t end = std::min(frontier + kChunk, capacity_);
        // clip against live extents: an allocated range is the owner's to
        // fault (its writer touches it anyway); only free space is memset
        uint64_t cur = frontier;
        while (cur < end) {
          uint64_t next_alloc = end, alloc_end = 0;
          for (const auto& kv : extents_) {
            if (kv.first + kv.second > cur && kv.first < next_alloc) {
              next_alloc = std::max(kv.first, cur);
              alloc_end = kv.first + kv.second;
            }
          }
          if (next_alloc > cur) {
            memset(static_cast<char*>(base_) + cur, 0, next_alloc - cur);
          }
          cur = next_alloc < end ? std::max(alloc_end, next_alloc) : end;
        }
        frontier = end;
      }
      std::this_thread::yield();
    }
  }

  std::string name_;
  uint64_t capacity_;
  int fd_ = -1;
  void* base_ = nullptr;
  std::mutex mu_;
  std::unordered_map<std::string, Object> objects_;
  std::list<FreeBlock> free_list_;                // address-ordered
  std::unordered_map<uint64_t, uint64_t> extents_;  // offset -> alloc size
  uint64_t used_ = 0;
  uint64_t tick_ = 0;
  uint64_t num_evicted_ = 0;
  std::thread toucher_;
  std::atomic<bool> stop_{false};
  std::atomic<bool> leak_mapping_{false};
};

}  // namespace

extern "C" {

void* rtpu_store_create(const char* name, uint64_t capacity) {
  auto* s = new ShmArenaStore(name, capacity);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void rtpu_store_destroy(void* store) {
  delete static_cast<ShmArenaStore*>(store);
}

int rtpu_store_put(void* store, const char* id, uint64_t size,
                   uint64_t* offset_out, char* evicted_buf,
                   uint64_t evicted_cap) {
  return static_cast<ShmArenaStore*>(store)->Put(id, size, offset_out,
                                                 evicted_buf, evicted_cap);
}

int rtpu_store_seal(void* store, const char* id) {
  return static_cast<ShmArenaStore*>(store)->Seal(id);
}

int rtpu_store_get(void* store, const char* id, uint64_t* offset,
                   uint64_t* size, int* sealed) {
  return static_cast<ShmArenaStore*>(store)->Get(id, offset, size, sealed);
}

int rtpu_store_pin(void* store, const char* id, int pinned) {
  return static_cast<ShmArenaStore*>(store)->Pin(id, pinned);
}

int rtpu_store_delete(void* store, const char* id) {
  return static_cast<ShmArenaStore*>(store)->Delete(id);
}

void rtpu_store_stats(void* store, uint64_t* used, uint64_t* num_objects,
                      uint64_t* num_evicted, uint64_t* capacity) {
  static_cast<ShmArenaStore*>(store)->Stats(used, num_objects, num_evicted,
                                            capacity);
}

// Direct write/read helpers for the agent process (tests + local fast path).
void* rtpu_store_base(void* store) {
  return static_cast<ShmArenaStore*>(store)->base();
}

// Keep the arena mapped after destroy (in-process views may outlive the
// store object; pages are reclaimed at process exit).
void rtpu_store_leak_mapping(void* store) {
  static_cast<ShmArenaStore*>(store)->LeakMapping();
}

}  // extern "C"
