"""Test fixtures.

Mirrors the reference's conftest keystones
(/root/reference/python/ray/tests/conftest.py — ray_start_regular:590,
ray_start_cluster:680): a single-node runtime fixture and an in-process
multi-node Cluster fixture. JAX tests run on a virtual 8-device CPU mesh
(SURVEY.md §4: keep everything runnable CPU-only).
"""

import os

# Must be set before jax import anywhere in the test process.
os.environ["JAX_PLATFORMS"] = "cpu"  # force: ambient env may say otherwise
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The TPU (axon) PJRT plugin registers itself as the default backend even when
# JAX_PLATFORMS=cpu is in the env; force the cpu platform explicitly so tests
# run on the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the jit-heavy suites (parallel, train,
# serve_llm, rllib) spend most of their wall time compiling the same tiny
# programs every run; cache them across files, runs AND worker subprocesses
# (env form inherits; jax.config wouldn't reach spawned workers). The
# reference keeps suite time down with long-lived shared clusters
# (conftest.py:590) — this is the JAX-native equivalent lever.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/ray_tpu_test_jit_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))

import pytest  # noqa: E402

# The suite must be unable to hang: any bare get()/wait() that would block
# forever raises in minutes instead (inherited by worker subprocesses).
os.environ.setdefault("RAY_TPU_BLOCKING_WATCHDOG_S", "300")

# Hang forensics. The blocking watchdog covers get()/wait(); a deadlock on
# a raw Lock/Condition it cannot see. Arm a per-test stack-dump timer: any
# test stuck longer than PER_TEST_HANG_DUMP_S dumps EVERY thread's stack
# and aborts the run — a silent futex park becomes a diagnosable failure.
# SIGUSR1 dumps stacks on demand for a live run (kill -USR1 <pytest pid>).
import faulthandler  # noqa: E402
import signal  # noqa: E402

PER_TEST_HANG_DUMP_S = float(os.environ.get("PER_TEST_HANG_DUMP_S", "480"))
# A REAL file, not sys.stderr: under pytest's fd-level capture a default
# dump lands in the per-test capture tempfile and vanishes with the process.
HANG_DUMP_PATH = os.environ.get("HANG_DUMP_PATH", "/tmp/ray_tpu_hang_dump.txt")
_hang_dump_file = open(HANG_DUMP_PATH, "a")  # noqa: SIM115 — lives forever
try:
    faulthandler.register(signal.SIGUSR1, all_threads=True,
                          file=_hang_dump_file)
except (AttributeError, ValueError):  # non-main thread / unsupported
    pass

# Custom watchdog instead of faulthandler.dump_traceback_later: that caps
# the dump at 100 threads and the suite accumulates several hundred daemon
# threads — the main thread and the actual lock holder land in the
# truncated tail. This dumper names every thread and has no cap.
import sys  # noqa: E402
import threading as _threading  # noqa: E402
import traceback as _traceback  # noqa: E402

_watchdog_timer = None


def _dump_all_threads_and_exit(nodeid: str):
    names = {t.ident: t.name for t in _threading.enumerate()}
    f = _hang_dump_file
    f.write(f"\n!!! HANG ({PER_TEST_HANG_DUMP_S:.0f}s) in {nodeid}\n")
    for tid, frame in sys._current_frames().items():
        f.write(f"\n--- thread {names.get(tid, '?')} ({tid})\n")
        f.write("".join(_traceback.format_stack(frame)))
    f.flush()
    os._exit(70)


@pytest.fixture(autouse=True)
def _hang_dump(request):
    global _watchdog_timer
    _hang_dump_file.write(f"=== arm: {request.node.nodeid}\n")
    _hang_dump_file.flush()
    _watchdog_timer = _threading.Timer(
        PER_TEST_HANG_DUMP_S, _dump_all_threads_and_exit,
        args=(request.node.nodeid,))
    _watchdog_timer.daemon = True
    _watchdog_timer.start()
    yield
    _watchdog_timer.cancel()


@pytest.fixture(scope="module")
def ray_start_module():
    """Module-scoped cluster (reference conftest.py:590 fixture reuse):
    tests that exercise the public API without killing cluster components
    share one runtime per file. Generous LOGICAL cpus — actors from
    earlier tests in the module stay alive and each reserves one."""
    import ray_tpu
    ray_tpu.shutdown()
    ctx = ray_tpu.init(num_cpus=64, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    import ray_tpu
    ray_tpu.shutdown()
    ctx = ray_tpu.init(num_cpus=4, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.core.cluster import Cluster
    import ray_tpu
    ray_tpu.shutdown()
    cluster = Cluster()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@pytest.fixture
def jax_cpu_mesh():
    import jax
    devices = jax.devices("cpu")
    assert len(devices) >= 8, "need 8 virtual cpu devices"
    yield devices
