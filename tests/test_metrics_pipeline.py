"""Cluster-wide metrics pipeline tests (ISSUE 4).

Covers the flusher -> CP time-series store -> query/exposition path:
built-in runtime series appearing without manual pushes, time-bounded
queries, cross-worker histogram merging, dead-worker series retraction,
and the serve percentile views. Fake-clock scenarios inject delta
snapshots directly through the `metrics_report` RPC with explicit
timestamps — the store honors the caller's clock.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.util import state
from ray_tpu.util.metrics import percentiles_from_buckets


@pytest.fixture
def metrics_cluster():
    ray_tpu.shutdown()
    ctx = ray_tpu.init(num_cpus=4, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
        "metrics_flush_interval_s": 0.2,
    })
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def _cp():
    from ray_tpu.core import api
    return api._get_runtime().cp_client


def _report(source, ts, metrics, node_id=None):
    return _cp().call("metrics_report", {
        "source": source, "node_id": node_id, "ts": ts,
        "metrics": metrics}, timeout=10.0)


def _hist_md(name, boundaries, tag_keys, series):
    return {"name": name, "kind": "histogram", "description": name,
            "tag_keys": list(tag_keys), "boundaries": list(boundaries),
            "series": series}


def _wait_for(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


def test_builtin_series_flow_without_manual_push(metrics_cluster):
    """A task + serve round-trip lands built-in series in the CP store via
    the auto-flushers alone — no explicit push anywhere."""

    @ray_tpu.remote
    def add(x):
        return x + 1

    assert ray_tpu.get([add.remote(i) for i in range(10)]) == list(
        range(1, 11))

    @serve.deployment
    def echo(payload):
        return {"got": payload}

    serve.run(echo.bind(), name="mapp", route_prefix="/m")
    proxy = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{proxy.port}/m",
        data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"})
    body = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert body == {"got": {"a": 1}}

    def have(*names):
        stored = {r["name"] for r in state.list_metric_series()}
        return all(n in stored for n in names)

    _wait_for(
        lambda: have("ray_tpu_task_lifecycle_seconds",
                     "ray_tpu_rpc_request_latency_seconds",
                     "ray_tpu_task_latency_seconds",
                     "ray_tpu_node_agent_workers",
                     "ray_tpu_serve_replica_processing_seconds",
                     "ray_tpu_serve_request_latency_seconds"),
        msg="built-in series in the CP store")

    # the lifecycle histogram saw the 10 completions
    q = state.query_metrics("ray_tpu_task_lifecycle_seconds",
                            tags={"transition": "completed"})
    assert q is not None and q["merged"]["count"] >= 10

    # proxy series carries deployment/route/status tags
    q = state.query_metrics("ray_tpu_serve_request_latency_seconds",
                            tags={"deployment": "echo", "route": "/m",
                                  "status": "200"})
    assert q is not None and q["merged"] is not None
    assert q["merged"]["count"] >= 1
    serve.delete("mapp")


def test_metrics_query_time_bounded(metrics_cluster):
    md = {"name": "fake_clock_gauge", "kind": "gauge",
          "description": "g", "tag_keys": [], "series": []}
    for ts, val in ((1000.0, 1.0), (2000.0, 2.0), (3000.0, 3.0)):
        r = _report("fake-src", ts, [
            {**md, "series": [{"tags": [], "value": val}]}])
        assert r and r.get("ok")

    q = state.query_metrics("fake_clock_gauge", since=1500.0, until=2500.0)
    assert q is not None
    pts = [p for s in q["series"] for p in s["points"]]
    assert pts == [[2000.0, 2.0]]

    # unbounded: all three, in order
    q = state.query_metrics("fake_clock_gauge")
    pts = [p for s in q["series"] for p in s["points"]]
    assert [p[0] for p in pts] == [1000.0, 2000.0, 3000.0]
    assert state.query_metrics("never_reported_metric") is None


def test_histogram_merge_across_two_workers(metrics_cluster):
    bounds = [0.1, 1.0]
    name = "merge_hist"
    # worker 1 reports twice (deltas accumulate into cumulative store-side)
    _report("w1", 100.0, [_hist_md(name, bounds, [], [
        {"tags": [], "buckets": [1, 2, 0], "sum": 1.0, "count": 3}])])
    _report("w1", 101.0, [_hist_md(name, bounds, [], [
        {"tags": [], "buckets": [0, 1, 1], "sum": 2.5, "count": 2}])])
    # worker 2 reports once
    _report("w2", 102.0, [_hist_md(name, bounds, [], [
        {"tags": [], "buckets": [2, 0, 1], "sum": 3.0, "count": 3}])])

    q = state.query_metrics(name)
    assert q is not None
    by_source = {s["source"]: s["points"][-1][1] for s in q["series"]}
    assert by_source["w1"]["buckets"] == [1, 3, 1]  # cumulative across flushes
    assert by_source["w2"]["buckets"] == [2, 0, 1]
    merged = q["merged"]
    assert merged["buckets"] == [3, 3, 2]
    assert merged["count"] == 8
    assert abs(merged["sum"] - 6.5) < 1e-9

    # exposition: ONE series (merged), cumulative le-buckets, no duplicates
    text = _cp().call("get_metrics", None, timeout=10.0)
    lines = [ln for ln in text.splitlines() if ln.startswith(name)]
    assert f'{name}_bucket{{le="0.1"}} 3' in lines
    assert f'{name}_bucket{{le="1.0"}} 6' in lines
    assert f'{name}_bucket{{le="+Inf"}} 8' in lines
    assert f'{name}_count 8' in lines
    assert len([ln for ln in lines if ln.startswith(f"{name}_count")]) == 1
    assert len([ln for ln in text.splitlines()
                if ln.startswith(f"# TYPE {name} ")]) == 1


def test_dead_worker_series_retracted(metrics_cluster):
    src = "deadbeef01"
    r = _report(src, time.time(), [
        {"name": "doomed_gauge", "kind": "gauge", "description": "",
         "tag_keys": [], "series": [{"tags": [], "value": 7.0}]}])
    assert r and r.get("ok")
    # legacy `metrics:<worker>` KV blobs no longer ride the scrape — the
    # registry/flusher pipeline is the only exposition source
    _cp().call("kv_put", {"key": f"metrics:{src}",
                          "value": b"legacy_series 1\n", "overwrite": True})
    assert any(row["name"] == "doomed_gauge"
               for row in state.list_metric_series())
    assert "legacy_series" not in _cp().call("get_metrics", None,
                                             timeout=10.0)

    _cp().call("worker_died", {"worker_id": src, "reason": "test kill"})

    assert not any(row["name"] == "doomed_gauge"
                   for row in state.list_metric_series())
    text = _cp().call("get_metrics", None, timeout=10.0)
    assert "doomed_gauge" not in text
    # late flush from the dead worker is refused, not resurrected
    r = _report(src, time.time(), [
        {"name": "doomed_gauge", "kind": "gauge", "description": "",
         "tag_keys": [], "series": [{"tags": [], "value": 8.0}]}])
    assert r and r.get("retracted")
    assert not any(row["name"] == "doomed_gauge"
                   for row in state.list_metric_series())


def test_detailed_status_percentiles_from_fake_clock(metrics_cluster):
    @serve.deployment
    class Quiet:
        def __call__(self, x):
            return x

    serve.run(Quiet.bind(), name="papp", route_prefix=None)

    # inject a known latency distribution for the deployment, with the
    # replica histogram's schema (boundaries + deployment tag)
    bounds = [0.001, 0.01, 0.1, 1, 10, 100]
    buckets = [0, 500, 450, 50, 0, 0, 0]
    _report("fake-replica", time.time(), [_hist_md(
        "ray_tpu_serve_replica_processing_seconds", bounds,
        ["deployment"],
        [{"tags": ["Quiet"], "buckets": buckets,
          "sum": 25.0, "count": 1000}])])

    st = serve.detailed_status()
    lat = st["papp#Quiet"]["latency_ms"]
    assert lat is not None
    expect = percentiles_from_buckets(bounds, buckets)
    # the controller's own engine-stat probes may add a few sub-ms
    # observations; the injected 1000 points dominate
    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        assert lat[key] == pytest.approx(expect[q] * 1000.0, rel=0.10), key
    serve.delete("papp")


def test_flusher_buffers_across_send_outage(monkeypatch):
    """Satellite: a CP outage must not tear a hole in the time series.
    `snapshot_deltas` advances the registry baselines at snapshot time, so
    a dropped payload would lose those counter increments permanently.
    While the sink fails, each flush queues its payload with the ORIGINAL
    timestamp; on recovery everything is delivered oldest-first; the
    buffer is bounded by `metrics_flush_buffer_max` (oldest evicted)."""
    from ray_tpu.core.config import get_config
    from ray_tpu.util import metrics as um

    fake = [{"name": "x", "kind": "counter", "description": "",
             "tag_keys": [], "series": [{"tags": [], "delta": 1.0}]}]
    monkeypatch.setattr(um, "snapshot_deltas", lambda: [dict(d) for d in fake])

    sent, down = [], [True]

    def send(payload):
        if down[0]:
            raise ConnectionError("cp down")
        sent.append(payload)

    f = um.MetricsFlusher(send, source="unit", interval_s=999.0)
    tss = []
    for _ in range(5):
        f.flush()
        tss.append(f._backlog[-1]["ts"])
        time.sleep(0.01)
    assert sent == [] and len(f._backlog) == 5

    down[0] = False
    f.flush()  # recovery: backlog + the fresh snapshot all deliver
    assert len(sent) == 6 and not f._backlog
    # original timestamps preserved, oldest first — the store back-fills
    # the outage window instead of showing a gap
    assert [p["ts"] for p in sent[:5]] == tss == sorted(tss)
    total = sum(s["delta"] for p in sent
                for md in p["metrics"] for s in md["series"])
    assert total == 6.0  # every increment arrived exactly once

    # bounded: oldest payloads evicted beyond metrics_flush_buffer_max
    monkeypatch.setattr(get_config(), "metrics_flush_buffer_max", 3)
    down[0] = True
    for _ in range(6):
        f.flush()
        time.sleep(0.01)
    assert len(f._backlog) == 3  # cap trims oldest before each send pass
    down[0] = False
    f.flush()  # fresh snapshot joins, cap trims to 3 again, all deliver
    assert not f._backlog
    kept = sent[6:]
    assert len(kept) == 3
    assert [p["ts"] for p in kept] == sorted(p["ts"] for p in kept)


def test_metrics_no_gap_across_cp_outage():
    """Integration: a WORKER keeps incrementing a counter while the CP is
    down; its flusher buffers each interval's delta with the ORIGINAL
    timestamp and back-fills the store after the restart — the queried
    series has points INSIDE the outage window, not a hole. (The head
    process's own flusher is CP-owned and restarts with it; the buffering
    path under test is the cross-process worker/agent one.)"""
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address, _system_config={
        "metrics_flush_interval_s": 0.2,
    })
    try:
        @ray_tpu.remote
        class Prober:
            def __init__(self):
                from ray_tpu.util.metrics import Counter
                self.c = Counter("ft_outage_probe_total", "outage probe")

            def bump(self):
                self.c.inc()
                return True

        p = Prober.remote()
        assert ray_tpu.get(p.bump.remote(), timeout=60)
        # the worker's flusher is live once the series reaches the store
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if state.query_metrics("ft_outage_probe_total") is not None:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("probe series never reached the CP store")

        t_kill = time.time()
        addr = cluster.kill_control_plane()
        # ~1.5s outage; actor calls ride established data-plane channels,
        # and every 0.2s the worker flusher buffers a failed payload
        stop = time.time() + 1.5
        while time.time() < stop:
            ray_tpu.get(p.bump.remote(), timeout=30)
            time.sleep(0.1)
        cluster.restart_control_plane(addr)
        t_restart = time.time()

        ray_tpu.get(p.bump.remote(), timeout=30)
        deadline = time.monotonic() + 30.0
        pts = []
        while time.monotonic() < deadline:
            try:
                q = state.query_metrics("ft_outage_probe_total")
            except Exception:  # noqa: BLE001 — CP client reconnecting
                q = None
            pts = [p_ for s in (q or {}).get("series", ())
                   for p_ in s["points"]]
            if sum(1 for ts, _ in pts if t_kill <= ts <= t_restart) >= 3:
                break
            time.sleep(0.3)
        inside = [p_ for p_ in pts if t_kill <= p_[0] <= t_restart]
        assert len(inside) >= 3, (
            f"no back-filled points inside the {t_restart - t_kill:.1f}s "
            f"outage window — buffered worker flushes were dropped: {pts}")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
