"""multiprocessing.Pool API over the cluster.

TPU-native analog of the reference shim (python/ray/util/multiprocessing/
pool.py): drop-in ``Pool`` whose workers are cluster actors, so existing
multiprocessing code scales past one machine by changing an import. The
surface covered: map/starmap/apply (+ _async variants returning
AsyncResult), imap/imap_unordered, chunking, context manager,
close/terminate/join.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Optional

import ray_tpu
from ray_tpu.exceptions import TaskError


def _unwrap(exc: BaseException) -> BaseException:
    """mp.Pool re-raises the ORIGINAL exception type; the runtime delivers
    a TaskError wrapper — unwrap so `except ValueError:` keeps working."""
    cause = getattr(exc, "cause", None)
    return cause if isinstance(exc, TaskError) and cause is not None else exc


@ray_tpu.remote
class _PoolWorker:
    """One pool process (reference pool worker actor): runs pickled
    callables; keeps the initializer's side effects for its lifetime."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, func, chunk, star: bool) -> list:
        if star:
            return [func(*args) for args in chunk]
        return [func(item) for item in chunk]

    def run_call(self, func, args, kwargs):
        return func(*args, **(kwargs or {}))


class AsyncResult:
    """multiprocessing.pool.AsyncResult surface over object refs."""

    def __init__(self, refs: list, reassemble: Callable[[list], Any],
                 single: bool = False):
        self._refs = refs
        self._reassemble = reassemble
        self._single = single

    def get(self, timeout: Optional[float] = None) -> Any:
        try:
            out = self._reassemble(ray_tpu.get(self._refs, timeout=timeout))
        except TaskError as e:
            raise _unwrap(e) from None
        except TimeoutError:
            # mp.Pool parity: its TimeoutError subclasses ProcessError,
            # NOT the builtin — migrated except-clauses must still match
            import multiprocessing
            raise multiprocessing.TimeoutError() from None
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:  # noqa: BLE001 — mp.Pool semantics
            return False


class Pool:
    """Drop-in multiprocessing.Pool running on cluster actors."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs: tuple = (), maxtasksperchild: Optional[int] = None,
                 *, ray_remote_args: Optional[dict] = None):
        # maxtasksperchild accepted for signature parity and ignored —
        # actor workers do not accumulate per-process state the way forked
        # mp workers do (the reference shim ignores it too)
        del maxtasksperchild
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            total = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(1, int(total))
        if processes < 1:
            raise ValueError("Number of processes must be at least 1")
        self._n = processes
        cls = _PoolWorker
        if ray_remote_args:
            cls = _PoolWorker.options(**ray_remote_args)
        self._workers = [cls.remote(initializer, tuple(initargs))
                         for _ in range(processes)]
        self._rr = 0
        self._closed = False
        self._inflight: list = []  # refs close()/join() must wait out

    # -- internals ------------------------------------------------------
    def _next_worker(self):
        if self._closed:
            raise ValueError("Pool not running")
        w = self._workers[self._rr % self._n]
        self._rr += 1
        return w

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            # multiprocessing's heuristic: ~4 chunks per worker
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def _track(self, refs: list) -> list:
        # prune settled refs opportunistically so the list stays bounded
        if len(self._inflight) > 4 * self._n:
            done, pending = ray_tpu.wait(
                self._inflight, num_returns=len(self._inflight), timeout=0)
            self._inflight = list(pending)
        self._inflight.extend(refs)
        return refs

    def _map_refs(self, func, iterable, chunksize, star: bool):
        chunks, _ = self._chunks(iterable, chunksize)
        return self._track(
            [self._next_worker().run_chunk.remote(func, c, star)
             for c in chunks])

    # -- the mp.Pool surface --------------------------------------------
    def map(self, func, iterable, chunksize: Optional[int] = None) -> list:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        refs = self._map_refs(func, iterable, chunksize, star=False)
        return AsyncResult(refs, lambda outs: list(
            itertools.chain.from_iterable(outs)))

    def starmap(self, func, iterable,
                chunksize: Optional[int] = None) -> list:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable,
                      chunksize: Optional[int] = None) -> AsyncResult:
        refs = self._map_refs(func, iterable, chunksize, star=True)
        return AsyncResult(refs, lambda outs: list(
            itertools.chain.from_iterable(outs)))

    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (),
                    kwds: Optional[dict] = None, callback=None,
                    error_callback=None) -> AsyncResult:
        (ref,) = self._track(
            [self._next_worker().run_call.remote(func, tuple(args), kwds)])
        if callback is not None or error_callback is not None:
            # one blocking-get thread per in-flight callback (ref.future());
            # bounded in practice by the caller's dispatch window (joblib
            # pre-dispatches ~2*n_jobs batches)
            def on_done(fut):
                try:
                    value = fut.result()
                except Exception as e:  # noqa: BLE001 — mp semantics
                    if error_callback is not None:
                        error_callback(_unwrap(e))
                    return
                if callback is not None:
                    callback(value)
            ref.future().add_done_callback(on_done)
        return AsyncResult([ref], lambda outs: outs, single=True)

    def _lazy_chunks(self, iterable: Iterable, chunksize: int):
        it = iter(iterable)
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return
            yield chunk

    def imap(self, func, iterable, chunksize: int = 1):
        """Ordered lazy iteration: at most ~2 chunks per worker in flight
        (mp.Pool's incremental submission; an infinite iterable works)."""
        window = max(2, 2 * self._n)
        chunks = self._lazy_chunks(iterable, chunksize)
        refs = [self._track(
            [self._next_worker().run_chunk.remote(func, c, False)])[0]
            for c in itertools.islice(chunks, window)]
        while refs:
            ref = refs.pop(0)
            for c in itertools.islice(chunks, 1):
                refs.append(self._track(
                    [self._next_worker().run_chunk.remote(func, c, False)])[0])
            try:
                yield from ray_tpu.get(ref)
            except TaskError as e:
                raise _unwrap(e) from None

    def imap_unordered(self, func, iterable, chunksize: int = 1):
        window = max(2, 2 * self._n)
        chunks = self._lazy_chunks(iterable, chunksize)
        pending = [self._track(
            [self._next_worker().run_chunk.remote(func, c, False)])[0]
            for c in itertools.islice(chunks, window)]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            pending = list(pending)
            for c in itertools.islice(chunks, 1):
                pending.append(self._track(
                    [self._next_worker().run_chunk.remote(func, c, False)])[0])
            try:
                yield from ray_tpu.get(done[0])
            except TaskError as e:
                raise _unwrap(e) from None

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self._workers = []

    def join(self) -> None:
        """Wait for submitted work to finish, then release the workers
        (mp.Pool's close()+join() contract: in-flight tasks complete)."""
        if not self._closed:
            raise ValueError("Pool is still running")
        while self._inflight:
            # unbounded by contract (mp.Pool.join blocks until done);
            # bounded waits in a loop so a wedged cluster still leaves
            # the thread interruptible
            done, pending = ray_tpu.wait(
                self._inflight, num_returns=len(self._inflight),
                timeout=60.0)
            self._inflight = list(pending)
        self.terminate()

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
