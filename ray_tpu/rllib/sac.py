"""Discrete SAC (ref: rllib/algorithms/sac/ — re-shaped for the discrete
builtin envs; math per the discrete-SAC formulation of Christodoulou 2019).

Twin soft Q networks with polyak-averaged targets, a categorical policy,
and auto-tuned entropy temperature — replay on the host, all three updates
fused into one jitted step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.buffer import ReplayBuffer
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import mlp_apply, mlp_init


class SAC(Algorithm):
    def setup(self) -> None:
        kw = self.config.train_kwargs
        env = make_env(self.config.env_spec)
        obs_dim, n_act = env.observation_dim, env.num_actions
        self._buffer = ReplayBuffer(kw.get("buffer_size", 50_000), obs_dim,
                                    seed=self.config.seed)
        self._batch_size = kw.get("train_batch_size", 128)
        self._updates_per_iter = kw.get("updates_per_iter", 64)
        self._learn_start = kw.get("learning_starts", 500)
        self._tau = kw.get("tau", 0.01)  # polyak target rate
        # discrete target entropy: a fraction of the uniform-policy entropy
        self._target_entropy = kw.get(
            "target_entropy", 0.5 * float(np.log(n_act)))
        # initial temperature. Starting high (alpha=1) inflates the soft
        # bootstrap early ("entropy farming": Q learns that staying alive
        # collects alpha*H per step) and the inflated values linger long
        # after alpha anneals; start low and let the temperature loss raise
        # it only if the policy over-sharpens.
        init_alpha = kw.get("initial_alpha", 0.1)

        # twin Qs next to the base module's categorical policy
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(
            self.config.seed + 1), 3)
        sizes = [obs_dim, *self.config.hidden, n_act]
        self.params = {
            "pi": self.params["pi"],
            # the EnvRunner's sample path evaluates forward_train (logits +
            # value) for its batch metadata; SAC doesn't train a V head but
            # must keep one so rollouts work
            "vf": self.params["vf"],
            "q1": mlp_init(k1, sizes),
            "q2": mlp_init(k2, sizes),
            "log_alpha": jnp.asarray(float(np.log(init_alpha))),
        }
        self._target = {
            "q1": jax.tree.map(jnp.copy, self.params["q1"]),
            "q2": jax.tree.map(jnp.copy, self.params["q2"]),
        }
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params)
        gamma, tau = self.config.gamma, self._tau
        target_entropy = self._target_entropy

        def losses(params, target, b):
            logits = mlp_apply(params["pi"], b["obs"])
            logp = jax.nn.log_softmax(logits)
            probs = jnp.exp(logp)
            alpha = jnp.exp(params["log_alpha"])

            # soft state value under the CURRENT policy at s'
            nlogits = mlp_apply(params["pi"], b["next_obs"])
            nlogp = jax.nn.log_softmax(nlogits)
            nprobs = jnp.exp(nlogp)
            nq = jnp.minimum(mlp_apply(target["q1"], b["next_obs"]),
                             mlp_apply(target["q2"], b["next_obs"]))
            v_next = jnp.sum(nprobs * (nq - jax.lax.stop_gradient(alpha)
                                       * nlogp), axis=1)
            td_target = b["rewards"] + gamma * (1.0 - b["dones"]) * \
                jax.lax.stop_gradient(v_next)

            q1 = mlp_apply(params["q1"], b["obs"])
            q2 = mlp_apply(params["q2"], b["obs"])
            a = b["actions"][:, None]
            q1_sa = jnp.take_along_axis(q1, a, axis=1)[:, 0]
            q2_sa = jnp.take_along_axis(q2, a, axis=1)[:, 0]
            critic_loss = ((q1_sa - td_target) ** 2).mean() + \
                ((q2_sa - td_target) ** 2).mean()

            # actor: minimize E_s pi(s)·(alpha·log pi - min Q)
            q_min = jax.lax.stop_gradient(jnp.minimum(q1, q2))
            actor_loss = jnp.sum(
                probs * (jax.lax.stop_gradient(alpha) * logp - q_min),
                axis=1).mean()

            # temperature: drive policy entropy toward the target
            entropy = -jnp.sum(probs * logp, axis=1).mean()
            alpha_loss = params["log_alpha"] * jax.lax.stop_gradient(
                entropy - target_entropy)
            return critic_loss + actor_loss + alpha_loss, {
                "critic_loss": critic_loss, "actor_loss": actor_loss,
                "alpha": alpha, "entropy": entropy}

        @jax.jit
        def update(params, target, opt_state, b):
            (_, metrics), grads = jax.value_and_grad(
                losses, has_aux=True)(params, target, b)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, p: (1.0 - tau) * t + tau * p, target,
                {"q1": params["q1"], "q2": params["q2"]})
            return params, target, opt_state, metrics

        self._update = update

    def training_step(self) -> dict:
        cfg = self.config
        samples = self.runners.sample(self.params, cfg.rollout_steps,
                                      explore=True)
        for s in samples:
            self._buffer.add_batch(s)
        self._timesteps += cfg.rollout_steps * cfg.num_env_runners

        if len(self._buffer) < self._learn_start:
            return {"buffer_size": len(self._buffer)}

        metrics = {}
        for _ in range(self._updates_per_iter):
            b = self._buffer.sample(self._batch_size)
            self.params, self._target, self._opt_state, metrics = \
                self._update(self.params, self._target, self._opt_state, b)
        return {k: float(v) for k, v in metrics.items()} | {
            "buffer_size": len(self._buffer)}

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_cls=cls)
        cfg.lr = 3e-3
        return cfg


def SACConfig() -> AlgorithmConfig:
    return SAC.get_default_config()
