"""ActorPool (reference: /root/reference/python/ray/util/actor_pool.py):
round-robin work distribution over a fixed set of actors with
ordered/unordered result retrieval."""

from __future__ import annotations

from typing import Any, Callable, Iterator

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef; queues if all actors busy."""
        if self._idle:
            actor = self._idle.pop(0)
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: float | None = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        # Wait with the timeout BEFORE mutating pool state so a TimeoutError
        # leaves the pool intact (reference actor_pool.py does ray.wait first).
        future = self._index_to_future[self._next_return_index]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        try:
            result = ray_tpu.get(future)
        finally:
            self._return_actor(self._future_to_actor.pop(future))
        return result

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        for idx, f in list(self._index_to_future.items()):
            if f == future:
                del self._index_to_future[idx]
                break
        result = ray_tpu.get(future)
        self._return_actor(self._future_to_actor.pop(future))
        return result

    def map(self, fn: Callable, values: list) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: list) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop(0) if self._idle else None

    def push(self, actor):
        self._return_actor(actor)
