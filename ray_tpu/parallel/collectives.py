"""Host-level (control-plane) collectives between actors/workers.

TPU-native analog of the reference's two host-side collective layers:
- `ray.util.collective` (/root/reference/python/ray/util/collective/
  collective.py:166 init_collective_group; allreduce:311, broadcast:426,
  allgather:476, reducescatter:525, send:584, recv:647) — but ONLY for
  host/control data: device-to-device traffic is XLA collectives over ICI and
  never goes through here (SURVEY.md §2.3).
- Ray Train's SynchronizationActor barrier/broadcast
  (python/ray/train/collective/collectives.py,
  train/v2/_internal/execution/collective_impl.py:17,33).

Groups rendezvous through a named actor, like the reference's named-actor
group store (collective_group/base_collective_group.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any

import ray_tpu


@ray_tpu.remote(num_cpus=0.1)
class SyncActor:
    """Rendezvous actor: barrier / broadcast / allgather / reduce for a fixed
    world size (ref: checkpoint/sync_actor.py:27)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._round = 0
        self._arrived: dict[int, Any] = {}
        self._results: dict[int, list] = {}

    def arrive(self, rank: int, round_id: int, value=None):
        """Returns (done, gathered values or None)."""
        self._arrived.setdefault(round_id, {})
        self._arrived[round_id][rank] = value
        if len(self._arrived[round_id]) >= self.world_size:
            vals = [self._arrived[round_id].get(r) for r in range(self.world_size)]
            self._results[round_id] = vals
        return self._results.get(round_id)

    def poll(self, round_id: int):
        return self._results.get(round_id)

    def reset(self):
        self._arrived.clear()
        self._results.clear()


class CollectiveGroup:
    """Per-process handle onto a named sync actor."""

    def __init__(self, name: str, world_size: int, rank: int):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._round = 0
        self._lock = threading.Lock()
        if rank == 0:
            self._actor = SyncActor.options(name=f"collective:{name}").remote(world_size)
        else:
            deadline = time.monotonic() + 60
            while True:
                try:
                    self._actor = ray_tpu.get_actor(f"collective:{name}", timeout=5.0)
                    break
                except ValueError:
                    if time.monotonic() > deadline:
                        raise

    def _next_round(self) -> int:
        with self._lock:
            self._round += 1
            return self._round

    def _rendezvous(self, value=None, timeout: float = 300.0) -> list:
        rid = self._next_round()
        result = ray_tpu.get(self._actor.arrive.remote(self.rank, rid, value),
                             timeout=timeout)
        deadline = time.monotonic() + timeout
        while result is None:
            time.sleep(0.01)
            result = ray_tpu.get(self._actor.poll.remote(rid), timeout=timeout)
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective {self.name} round {rid} timed out")
        return result

    def barrier(self, timeout: float = 300.0) -> None:
        self._rendezvous(None, timeout)

    def broadcast(self, value=None, src: int = 0, timeout: float = 300.0):
        vals = self._rendezvous(value if self.rank == src else None, timeout)
        return vals[src]

    def allgather(self, value, timeout: float = 300.0) -> list:
        return self._rendezvous(value, timeout)

    def allreduce(self, value, op=None, timeout: float = 300.0):
        vals = self._rendezvous(value, timeout)
        if op is None:
            out = vals[0]
            for v in vals[1:]:
                out = out + v
            return out
        import functools
        return functools.reduce(op, vals)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    """(ref: util/collective/collective.py:166)"""
    return CollectiveGroup(group_name, world_size, rank)
