"""Pluggable control-plane metadata storage.

TPU-native analog of the reference's GCS storage backends
(/root/reference/src/ray/gcs/store_client/ — InMemoryStoreClient,
RedisStoreClient for fault tolerance; replay via gcs_init_data.cc): the
control plane writes every durable mutation (KV, jobs, actor records, PGs)
through this interface, and on restart replays `load_all` per section.

Backends:
- MemoryMetaStore: default; no durability (CP death = cluster loss).
- SqliteMetaStore: single-file WAL-mode sqlite — the single-node analog of
  Redis-backed GCS FT. Safe for one writer (the CP) + crash recovery.
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Iterator


class MemoryMetaStore:
    def __init__(self):
        self._data: dict[tuple[str, bytes], bytes] = {}
        self._lock = threading.Lock()

    def save(self, section: str, key: bytes, obj: Any) -> None:
        with self._lock:
            self._data[(section, bytes(key))] = pickle.dumps(obj)

    def delete(self, section: str, key: bytes) -> None:
        with self._lock:
            self._data.pop((section, bytes(key)), None)

    def load_all(self, section: str) -> Iterator[tuple[bytes, Any]]:
        with self._lock:
            items = [(k[1], v) for k, v in self._data.items()
                     if k[0] == section]
        for key, blob in items:
            yield key, pickle.loads(blob)

    def close(self) -> None:
        pass


class SqliteMetaStore:
    def __init__(self, path: str):
        import sqlite3

        self.path = path
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta ("
            " section TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (section, key))")
        self._db.commit()

    def save(self, section: str, key: bytes, obj: Any) -> None:
        blob = pickle.dumps(obj)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO meta (section, key, value) "
                "VALUES (?, ?, ?)", (section, bytes(key), blob))
            self._db.commit()

    def delete(self, section: str, key: bytes) -> None:
        with self._lock:
            self._db.execute(
                "DELETE FROM meta WHERE section = ? AND key = ?",
                (section, bytes(key)))
            self._db.commit()

    def load_all(self, section: str) -> Iterator[tuple[bytes, Any]]:
        with self._lock:
            rows = self._db.execute(
                "SELECT key, value FROM meta WHERE section = ?",
                (section,)).fetchall()
        for key, blob in rows:
            yield key, pickle.loads(blob)

    def close(self) -> None:
        with self._lock:
            self._db.close()


def make_meta_store(path: str | None):
    return SqliteMetaStore(path) if path else MemoryMetaStore()
