"""Distributed tracing tests (models the reference's
python/ray/tests/test_tracing.py: spans propagate across task / actor
boundaries and stitch into one trace; here the store is the control
plane instead of an OTel collector, so assertions poll util/state).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.core.config import get_config
from ray_tpu.observability import tracing
from ray_tpu.util import state


@pytest.fixture(scope="module")
def tracing_cluster():
    ray_tpu.shutdown()
    ctx = ray_tpu.init(num_cpus=16, _system_config={
        "tracing_enabled": True,
        "tracing_sample_rate": 1.0,
        # tiny batch: spans must not sit in worker buffers for the whole
        # test — exercises the batch-full flush path too
        "trace_flush_batch": 4,
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })
    yield ctx
    ray_tpu.shutdown()


def _wait_trace(match, min_spans=1, timeout=40.0):
    """Poll the CP trace store until a trace matching `match(meta)` has
    at least `min_spans` spans (workers flush asynchronously)."""
    deadline = time.time() + timeout
    last = []
    while time.time() < deadline:
        last = state.list_traces(limit=50)
        for meta in last:
            if meta["num_spans"] >= min_spans and match(meta):
                return meta
        time.sleep(0.25)
    raise AssertionError(f"no matching trace with >={min_spans} spans; "
                         f"store has: {last}")


# ---- cross-process propagation ------------------------------------------

def test_nested_fanout_single_trace(tracing_cluster):
    """Driver -> task -> (nested task + actor create + actor call) is ONE
    stitched trace; every span shares the trace id and parents resolve."""

    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    class Counter:
        def bump(self, x):
            return x * 2

    @ray_tpu.remote
    def parent(x):
        c = Counter.remote()
        y = ray_tpu.get(child.remote(x))
        return ray_tpu.get(c.bump.remote(y))

    assert ray_tpu.get(parent.remote(1)) == 4

    meta = _wait_trace(lambda m: m["name"] == "task.submit:parent")
    assert meta["root_seen"]

    expected = ("task.submit:parent", "task.run:parent",
                "task.submit:child", "task.run:child",
                "actor.create:Counter", "lease.acquire")
    # workers flush independently; poll until every expected span landed
    deadline = time.time() + 40
    while True:
        trace = state.get_trace(meta["trace_id"])
        spans = trace["spans"]
        names = [s["name"] for s in spans]
        if all(e in names for e in expected):
            break
        assert time.time() < deadline, (expected, names)
        time.sleep(0.25)

    assert {s["trace_id"] for s in spans} == {meta["trace_id"]}
    # actor method call: submit side + execute side
    assert any(n.startswith("actor.submit:") for n in names)
    assert any(n.startswith("actor.run:") for n in names)

    # exactly one root; every other span's parent is a span in this trace
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "task.submit:parent"
    for s in spans:
        if s["parent_id"] is not None:
            assert s["parent_id"] in by_id, s["name"]

    # execution spans ran in different processes than the driver submit
    run = next(s for s in spans if s["name"] == "task.run:parent")
    sub = next(s for s in spans if s["name"] == "task.submit:parent")
    assert run["pid"] != sub["pid"]


def test_trace_exports_chrome_and_otlp(tracing_cluster, tmp_path):
    @ray_tpu.remote
    def ping():
        return "pong"

    assert ray_tpu.get(ping.remote()) == "pong"
    # 2 spans minimum: submit + run (lease.acquire only appears when the
    # submitter actually had to request a lease rather than reuse one)
    meta = _wait_trace(lambda m: m["name"] == "task.submit:ping",
                       min_spans=2)

    # prefix lookup (CLI ergonomics: `ray-tpu trace <id8>`)
    trace = state.get_trace(meta["trace_id"][:8])
    assert trace and trace["trace_id"] == meta["trace_id"]

    events = json.loads(state.trace_timeline(meta["trace_id"]))
    assert len(events) >= meta["num_spans"]
    for ev in events:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert ev["name"]

    otlp = json.loads(
        state.trace_timeline(meta["trace_id"], fmt="otlp"))
    scope_spans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(scope_spans) >= meta["num_spans"]
    for sp in scope_spans:
        assert sp["traceId"] == meta["trace_id"]
        assert int(sp["endTimeUnixNano"]) >= int(sp["startTimeUnixNano"])

    # file export path (what the CLI --out flag uses)
    out = tmp_path / "trace.json"
    assert state.trace_timeline(meta["trace_id"], filename=str(out)) is None
    assert json.loads(out.read_text())


def test_serve_http_request_single_trace(tracing_cluster):
    """One HTTP request through the proxy produces one stitched trace
    rooted at the proxy span, with the replica execution inside it."""
    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, body):
            return {"got": body}

    serve.run(Echo.bind(), name="traceapp", route_prefix="/traced")
    proxy = serve.start_http_proxy(port=18127)
    try:
        import urllib.request
        req = urllib.request.Request(
            "http://127.0.0.1:18127/traced",
            data=json.dumps({"k": 1}).encode(),
            headers={"Content-Type": "application/json"})
        body = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert body == {"got": {"k": 1}}

        meta = _wait_trace(
            lambda m: m["name"].startswith("http.request:"), min_spans=2)
        trace = state.get_trace(meta["trace_id"])
        names = [s["name"] for s in trace["spans"]]
        assert any(n.startswith("http.request:") for n in names)
        assert any(n.startswith("actor.run:") for n in names), names
        assert {s["trace_id"] for s in trace["spans"]} \
            == {meta["trace_id"]}
    finally:
        proxy.stop()
        serve.delete("traceapp")


# ---- sampling / local span mechanics (no cluster) -----------------------

@pytest.fixture
def span_capture(monkeypatch):
    """Capture flushed batches without disturbing a live runtime's sink."""
    batches = []
    old = tracing._flusher
    tracing.flush()  # drain anything a prior test left buffered
    tracing.register_flusher(lambda spans: batches.append(spans))
    yield batches
    tracing.flush()
    tracing.register_flusher(old)


def test_tracing_disabled_is_noop(monkeypatch, span_capture):
    monkeypatch.setattr(get_config(), "tracing_enabled", False)
    with tracing.span("root") as s:
        assert s is None
        assert tracing.inject() is None
    tracing.flush()
    assert span_capture == []


def test_sample_rate_zero_no_spans(monkeypatch, span_capture):
    monkeypatch.setattr(get_config(), "tracing_enabled", True)
    monkeypatch.setattr(get_config(), "tracing_sample_rate", 0.0)
    for _ in range(20):
        with tracing.span("root") as s:
            assert s is None
    # child_only spans never root, even at rate 1.0
    monkeypatch.setattr(get_config(), "tracing_sample_rate", 1.0)
    with tracing.span("hot", child_only=True) as s:
        assert s is None
    # unsampled specs carry no context -> workers are hard no-ops
    with tracing.span_from(None, "task.run:x") as s:
        assert s is None
    tracing.flush()
    assert span_capture == []


def test_propagation_decision_by_presence(monkeypatch, span_capture):
    """The sampling decision travels by carrier PRESENCE: a carrier makes
    spans even where local config says disabled (remote processes honor
    the root's decision)."""
    monkeypatch.setattr(get_config(), "tracing_enabled", False)
    carrier = {"trace_id": "ab" * 16, "span_id": "cd" * 8}
    with tracing.span_from(carrier, "task.run:x") as s:
        assert s is not None
        assert s["trace_id"] == carrier["trace_id"]
        assert s["parent_id"] == carrier["span_id"]
        assert tracing.inject() == {"trace_id": s["trace_id"],
                                    "span_id": s["span_id"]}
    tracing.flush()
    flat = [s for b in span_capture for s in b]
    assert [s["name"] for s in flat] == ["task.run:x"]


def test_flush_batching(monkeypatch, span_capture):
    monkeypatch.setattr(get_config(), "tracing_enabled", True)
    monkeypatch.setattr(get_config(), "tracing_sample_rate", 1.0)
    monkeypatch.setattr(get_config(), "trace_flush_batch", 3)
    with tracing.span("outer"):
        for i in range(7):
            with tracing.span(f"child-{i}"):
                pass
    # children flush in batches of 3 while `outer` is open; the unwind to
    # an empty stack flushes the remainder (child-6 + outer)
    assert [len(b) for b in span_capture] == [3, 3, 2]
    flat = [s for b in span_capture for s in b]
    assert len({s["trace_id"] for s in flat}) == 1
    outer = next(s for s in flat if s["name"] == "outer")
    assert all(s["parent_id"] == outer["span_id"]
               for s in flat if s is not outer)


def test_error_span_status(monkeypatch, span_capture):
    monkeypatch.setattr(get_config(), "tracing_enabled", True)
    monkeypatch.setattr(get_config(), "tracing_sample_rate", 1.0)
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("nope")
    tracing.flush()
    flat = [s for b in span_capture for s in b]
    assert flat[0]["status"] == "error"
    assert flat[0]["attrs"]["error"] == "ValueError"


def test_record_span_requires_parent(monkeypatch, span_capture):
    monkeypatch.setattr(get_config(), "tracing_enabled", True)
    assert tracing.record_span("orphan", 0.0, 1.0, parent=None) is None
    parent = {"trace_id": "ef" * 16, "span_id": "01" * 8}
    s = tracing.record_span("lease.acquire", 1.0, 2.0, parent=parent,
                            kind="scheduler", attrs={"granted": True})
    assert s["parent_id"] == parent["span_id"]
    tracing.flush()
    flat = [sp for b in span_capture for sp in b]
    assert [sp["name"] for sp in flat] == ["lease.acquire"]


def test_exporters_pure(monkeypatch):
    monkeypatch.setattr(get_config(), "tracing_enabled", True)
    parent = tracing.start_span("a", kind="submit", attrs={"n": 1})
    child = tracing.start_span(
        "b", parent={"trace_id": parent["trace_id"],
                     "span_id": parent["span_id"]})
    child["end"] = child["start"] + 0.5
    parent["end"] = parent["start"] + 1.0
    spans = [parent, child]

    events = tracing.to_chrome_trace(spans)
    assert [e["name"] for e in events] == ["a", "b"]
    assert events[0]["dur"] == pytest.approx(1e6)

    otlp = tracing.to_otlp_json(spans, service_name="svc")
    res = otlp["resourceSpans"][0]
    svc = [a for a in res["resource"]["attributes"]
           if a["key"] == "service.name"]
    assert svc[0]["value"]["stringValue"] == "svc"
    out = res["scopeSpans"][0]["spans"]
    assert out[1]["parentSpanId"] == parent["span_id"]
    assert out[0]["attributes"][0]["key"] == "n"
