"""In-process simulated multi-node cluster for tests.

TPU-native analog of the reference's cluster_utils
(/root/reference/python/ray/cluster_utils.py — Cluster:135, add_node:202,
remove_node:286): N real node agents (each with its own shm store and real
worker subprocesses) against one control plane, all on one host — so
distributed scheduling and fault-tolerance tests run without hardware
(SURVEY.md §4 keystone (a)). TPU slice topologies are faked via node labels,
giving the fake slice-topology provider SURVEY.md §4 calls for.
"""

from __future__ import annotations

from ray_tpu.core.control_plane import ControlPlane
from ray_tpu.core.ids import NodeID
from ray_tpu.core.node_agent import NodeAgent


class Cluster:
    def __init__(self, store_path: str | None = None):
        self._store_path = store_path
        self.control_plane = ControlPlane(store_path=store_path)
        self.nodes: list[NodeAgent] = []

    def kill_control_plane(self) -> tuple[str, int]:
        """Simulate CP crash (no graceful teardown of cluster state);
        returns the address to restart on."""
        addr = self.control_plane.addr
        self.control_plane.stop()
        return addr

    def restart_control_plane(self, addr: tuple[str, int]) -> ControlPlane:
        """Restart the CP on the SAME address with the SAME durable store —
        agents re-register via heartbeat, clients reconnect via RPC retry
        (ref: gcs FT restart + NotifyGCSRestart)."""
        import time
        last: Exception | None = None
        for _ in range(50):  # the old listener may take a moment to release
            try:
                self.control_plane = ControlPlane(
                    host=addr[0], port=addr[1], store_path=self._store_path)
                return self.control_plane
            except OSError as e:
                last = e
                time.sleep(0.1)
        raise last

    @property
    def address(self) -> str:
        return f"{self.control_plane.addr[0]}:{self.control_plane.addr[1]}"

    def add_node(self, *, num_cpus: float = 1.0, resources: dict | None = None,
                 labels: dict | None = None,
                 object_store_memory: int | None = None,
                 tpu_slice: str | None = None, tpu_worker_id: int = 0,
                 tpu_chips: int = 4, pod_type: str = "v5p-16",
                 inproc_workers: bool = False) -> NodeAgent:
        """Add a node. ``tpu_slice`` fakes TPU slice membership via labels.
        ``inproc_workers`` hosts the node's workers as threads in this
        process (scale/autoscaler harness) instead of subprocesses."""
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        lab = dict(labels or {})
        if tpu_slice is not None:
            res.setdefault("TPU", float(tpu_chips))
            lab.update({"slice_name": tpu_slice, "tpu_worker_id": str(tpu_worker_id),
                        "pod_type": pod_type, "topology": ""})
        agent = NodeAgent(self.control_plane.addr, resources=res, labels=lab,
                          object_store_memory=object_store_memory,
                          inproc_workers=inproc_workers)
        self.nodes.append(agent)
        return agent

    def remove_node(self, agent: NodeAgent, graceful: bool = False):
        """Kill a node (ref: cluster_utils.py:286). Non-graceful stops the
        agent cold so health checks must detect the death. Graceful runs
        the full drain protocol — BLOCKING until in-flight leases finished
        and primary objects migrated — before stopping the agent."""
        if agent in self.nodes:
            self.nodes.remove(agent)
        if graceful:
            try:
                self.control_plane._h_drain_node(
                    {"node_id": agent.node_id, "wait": True,
                     "reason": "cluster.remove_node"})
            except Exception:
                pass
        agent.stop()

    def kill_node_by_id(self, node_id: NodeID):
        for agent in list(self.nodes):
            if agent.node_id == node_id:
                self.remove_node(agent)
                return

    def shutdown(self):
        for agent in list(self.nodes):
            agent.stop()
        self.nodes.clear()
        self.control_plane.stop()
