"""The autoscaling loop.

Reference: python/ray/autoscaler/v2/autoscaler.py:169
(update_autoscaling_state: read demand → compute target → instance manager
launches/terminates) + monitor.py's periodic drive. Demand = the control
plane's pending actors and placement-group bundles (get_pending_demand);
supply = registered alive nodes. One node type per autoscaler for now (a
TPU slice is the natural unit); layered node types can stack autoscalers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

from ray_tpu.autoscaler.instance_manager import InstanceManager, InstanceState
from ray_tpu.core.rpc import RpcClient
from ray_tpu.core.scheduler import fits

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    # what ONE HOST of a launched node provides (must match the provider)
    node_resources: dict = dataclasses.field(default_factory=dict)
    node_labels: dict = dataclasses.field(default_factory=dict)
    # hosts per provider node: a multi-host TPU slice launches as ONE
    # provider node whose create brings up all host agents (matching GCE,
    # where one slice create yields every host VM)
    hosts_per_node: int = 1
    idle_timeout_s: float = 60.0
    poll_interval_s: float = 1.0


class Autoscaler:
    def __init__(self, cp_addr: tuple[str, int], provider,
                 config: AutoscalerConfig):
        self._cp = RpcClient(tuple(cp_addr), name="autoscaler")
        self._provider = provider
        self._cfg = config
        self._stopped = threading.Event()
        self._idle_since: dict[str, float] = {}
        # provider nodes mid-drain: name -> drain-started monotonic ts.
        # Scale-down is two-phase (drain, THEN terminate) — the VM is only
        # released once every host finished draining or the deadline passed
        self._draining: dict[str, float] = {}
        # boots older than this stop counting against demand (the node may
        # have failed — allow a replacement); the instance manager is the
        # single source of what is booting (ALLOCATED instances)
        self.launch_grace_s = 600.0
        self._thread: threading.Thread | None = None
        # v2 instance lifecycle tracking (reference instance_manager):
        # every provider node walks QUEUED -> ... -> TERMINATED with a
        # recorded transition history
        self.instance_manager = InstanceManager(provider)
        import uuid as _uuid
        # stacked autoscalers (layered node types) each publish under
        # their own key; the dashboard merges the prefix like train_run:*
        self.scaler_id = _uuid.uuid4().hex[:8]
        self.num_launched = 0
        self.num_terminated = 0

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="autoscaler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # retract our published state: the per-scaler KV key would
        # otherwise outlive this scaler forever and the dashboard would
        # keep showing its dead instances (best-effort — the dashboard
        # also filters rows with stale updated_at)
        try:
            # the dashboard filters stale updated_at rows, so a lost
            # retraction only leaves a row the UI already hides
            # graftlint: fire-and-forget
            self._cp.notify(
                "kv_del", {"key": f"autoscaler:instances:{self.scaler_id}"})
        except Exception:  # noqa: BLE001 — CP may already be gone
            pass

    # ---- one reconciliation pass (public for tests) --------------------
    def update(self) -> None:
        demand = self._cp.call_with_retry("get_pending_demand", None,
                                          timeout=10.0)
        nodes = self._cp.call_with_retry("get_nodes", None, timeout=10.0)
        alive = [n for n in nodes if n["alive"]]
        shapes = list(demand["actor_shapes"]) + list(demand["bundle_shapes"])

        # how many pending shapes fit NOWHERE in the current cluster?
        unplaceable = 0
        avail = [dict(n["available"]) for n in alive]
        for shape in shapes:
            placed = False
            for a in avail:
                if fits(a, shape):
                    for k, v in shape.items():
                        a[k] = a.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unplaceable += 1

        # provider-name -> CP node mapping (cloud nodes carry a
        # provider_node_name label; the fake provider also exposes agent())
        now = time.monotonic()
        by_pname: dict[str, list] = {}
        for n in alive:
            pname = (n.get("labels") or {}).get("provider_node_name")
            if pname:
                by_pname.setdefault(pname, []).append(n)
        get_agents = getattr(
            self._provider, "agents",
            lambda _n: [a for a in [getattr(self._provider, "agent",
                                            lambda _x: None)(_n)] if a])

        def cp_nodes_for(name: str) -> list[dict]:
            """All CP nodes belonging to one provider node (a multi-host
            slice registers one CP node per host)."""
            nodes = by_pname.get(name)
            if nodes:
                return nodes
            addrs = {tuple(a.addr) for a in get_agents(name)}
            return [n for n in alive if tuple(n["addr"]) in addrs]

        hosts = max(1, self._cfg.hosts_per_node)
        self.instance_manager.reconcile(
            lambda n: len(cp_nodes_for(n)) >= hosts)
        cur = self._provider.non_terminated_nodes()
        # booting = ALLOCATED instances inside the grace window: counted
        # against demand (no double-launch while a node boots) and immune
        # to idle scale-down. The manager moved registered ones to
        # RAY_RUNNING in the reconcile above — one source of truth.
        wall_now = time.time()
        booting = {i.name for i in self.instance_manager.instances(
                       {InstanceState.ALLOCATED})
                   if wall_now - i.updated_at <= self.launch_grace_s}

        want_new = 0
        if unplaceable > 0 and self._cfg.node_resources:
            import math
            per_host_cap = max(
                1, int(min(self._cfg.node_resources.get(k, 0) / v
                           for s in shapes[:1] for k, v in s.items()
                           if v > 0) or 1))
            per_node_cap = per_host_cap * hosts
            want_new = min(
                math.ceil(unplaceable / per_node_cap) - len(booting),
                self._cfg.max_workers - len(cur))
        want_new = max(want_new, self._cfg.min_workers - len(cur))
        for _ in range(max(0, want_new)):
            inst = self.instance_manager.launch(
                {"resources": dict(self._cfg.node_resources),
                 "labels": dict(self._cfg.node_labels),
                 "hosts": hosts})
            if inst.state == InstanceState.ALLOCATION_FAILED:
                logger.warning("instance %s allocation failed: %s",
                               inst.instance_id[:8], inst.history[-1][3])
                continue
            booting.add(inst.name)
            self.num_launched += 1
            logger.info("autoscaler launched node %s (unplaceable=%d)",
                        inst.name, unplaceable)

        # scale down: provider nodes whose EVERY host is idle (full
        # availability) past the timeout — a slice terminates whole or not
        # at all. Two-phase (reference v2 drain-before-terminate): ask the
        # CP to DRAIN each host (in-flight leases finish, primary objects
        # migrate to a survivor), then release the VM only once every host
        # has finished draining (deregistered) — or the drain deadline plus
        # grace passed, so a wedged host cannot leak the instance forever.
        from ray_tpu.core.config import get_config as _get_config
        drain_limit_s = _get_config().drain_deadline_s + 30.0
        for name in list(self._provider.non_terminated_nodes()):
            nodes = cp_nodes_for(name)
            if name in self._draining:
                still = [n for n in nodes
                         if n.get("state", "ALIVE") in ("ALIVE", "DRAINING")]
                if still and now - self._draining[name] < drain_limit_s:
                    continue  # hosts still running in-flight work
                # count at decision time (same as num_launched): providers
                # drop the node from non_terminated_nodes() DURING the
                # call, so a post-call increment lets an observer see the
                # node gone with the counter still short. A failed call
                # (gcloud flake) must not inflate the counter — roll back
                # and retry next reconcile.
                self.num_terminated += 1
                if not self.instance_manager.begin_terminate(
                        name, "drained after idle timeout"):
                    self.num_terminated -= 1
                    logger.warning(
                        "terminate_node(%s) failed; will retry", name)
                    continue
                self._draining.pop(name, None)
                self._idle_since.pop(name, None)
                continue
            # a partially-registered slice is BOOTING, not idle: host 0 can
            # register minutes before host N on real TPU slices, and
            # draining it would churn launch/terminate forever while the
            # slice PG never places
            idle = (name not in booting
                    and len(nodes) >= hosts
                    and all(n["available"] == n["resources"] for n in nodes))
            if not idle:
                self._idle_since.pop(name, None)
                continue
            first = self._idle_since.setdefault(name, now)
            over_min = len(self._provider.non_terminated_nodes()) \
                > self._cfg.min_workers
            if over_min and now - first >= self._cfg.idle_timeout_s:
                logger.info("autoscaler draining idle node %s", name)
                any_drain = False
                for node in nodes:
                    try:
                        self._cp.call(
                            "drain_node",
                            {"node_id": node["node_id"],
                             "reason": "autoscaler scale-down"}, timeout=10.0)
                        any_drain = True
                    except Exception:  # noqa: BLE001 — retry next reconcile
                        pass
                if any_drain:
                    self._draining[name] = now

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                self.update()
                self._publish_state()
            except Exception:  # noqa: BLE001
                logger.exception("autoscaler update failed")
            self._stopped.wait(self._cfg.poll_interval_s)

    def _publish_state(self) -> None:
        """Export instance lifecycle state to the CP KV for the dashboard
        (the train-run publishing pattern; reference: autoscaler state in
        the dashboard's cluster view). Best-effort."""
        import json as _json
        try:
            payload = {
                "summary": self.instance_manager.summary(),
                "num_launched": self.num_launched,
                "num_terminated": self.num_terminated,
                "instances": [i.to_dict() for i in
                              self.instance_manager.instances()][-100:],
                "updated_at": time.time(),
            }
            # periodic full-state publish; the next reconcile pass
            # overwrites any lost update
            # graftlint: fire-and-forget
            self._cp.notify("kv_put", {
                "key": f"autoscaler:instances:{self.scaler_id}",
                "value": _json.dumps(payload, default=str).encode()})
        except Exception:  # noqa: BLE001 — observability must not kill scaling
            pass
