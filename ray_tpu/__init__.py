"""ray_tpu — a TPU-native distributed AI runtime.

A brand-new framework with the capabilities of the reference Ray runtime
(surveyed in /root/repo/SURVEY.md), re-designed TPU-first: the device data
plane is XLA collectives over ICI/DCN meshes (pjit/shard_map/pallas), the
control plane is an ownership-based task/actor runtime with a slice-topology-
aware scheduler, and the object store understands device residency.

Public surface mirrors the reference's `ray` module
(/root/reference/python/ray/__init__.py).
"""

import os as _os

# pyarrow's bundled mimalloc pool segfaults under this runtime's thread
# profile (short-lived executor threads creating/freeing tables — reproduced
# reliably with batched arrow-returning tasks; exit code -11 in
# pa.Table construction/nbytes, gone with the system pool). Default every
# ray_tpu process to the system allocator BEFORE pyarrow can be imported;
# users can still override by setting the variable themselves.
_os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")

from ray_tpu.core.api import (
    available_resources,
    cancel,
    cluster_resources,
    exit_actor,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.streaming import ObjectRefGenerator
from ray_tpu.core.actor import ActorClass, ActorHandle, method
from ray_tpu.core.placement_group import (
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
    tpu_slice_placement_group,
)
from ray_tpu.core.task_spec import (
    NodeAffinityStrategy,
    NodeLabelStrategy,
    SpreadStrategy,
)
from ray_tpu import exceptions

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method",
    "get", "put", "wait",
    "kill", "cancel", "get_actor", "exit_actor", "get_runtime_context",
    "cluster_resources", "available_resources", "nodes",
    "ObjectRef", "ObjectRefGenerator", "ActorClass", "ActorHandle",
    "PlacementGroup", "placement_group", "remove_placement_group",
    "placement_group_table", "tpu_slice_placement_group",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinityStrategy", "NodeLabelStrategy", "SpreadStrategy",
    "exceptions", "__version__",
]
