"""Prefill/decode disaggregation tests (reference:
python/ray/llm/_internal/serve/deployments/prefill_decode_disagg/
prefill_decode_disagg.py + its serve tests). Tiny-Llama on CPU."""

import json
import urllib.request

import numpy as np
import pytest

import ray_tpu


def _tiny_cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=64, max_seq_len=128, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


def test_prefill_handoff_matches_monolithic():
    """A prompt prefilled on engine A and decoded on engine B must emit the
    same greedy tokens as one engine doing both — the KV pages really carry
    the prompt state across the handoff."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.disagg import DecodeEngine, prefill_only
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _tiny_cfg(max_tokens=6)
    mc = cfg.llama()
    params = llama.init_params(jax.random.PRNGKey(3), mc)

    mono = LLMEngine(cfg, params=params)
    mono.start()
    want = mono.generate([7, 3, 9, 1, 4] * 4, max_tokens=6,
                         temperature=0.0)["tokens"]
    mono.shutdown()

    pre = LLMEngine(cfg, params=params)       # prefill role: loop NOT started
    dec = DecodeEngine(cfg, params=params)    # decode role
    dec.start()
    try:
        state = prefill_only(pre, [7, 3, 9, 1, 4] * 4, temperature=0.0)
        assert state["plen"] == 20
        assert state["kv_k"].shape[2] == state["n_pages"]
        rid = dec.submit_prefilled(state, max_tokens=6)
        got = dec.result(rid, timeout=120.0)
        assert got["error"] is None
        assert got["tokens"] == want
        # pages recycled on both sides
        assert pre.engine_stats()["free_pages"] == cfg.num_pages - 1
    finally:
        dec.shutdown()


def test_disagg_decode_concurrency_and_page_recycling():
    """Several prefilled requests stream through one decode engine; slots
    and pages fully recycle."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.disagg import DecodeEngine, prefill_only
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _tiny_cfg(max_batch_size=2, num_pages=32, max_tokens=5)
    mc = cfg.llama()
    params = llama.init_params(jax.random.PRNGKey(5), mc)
    pre = LLMEngine(cfg, params=params)
    dec = DecodeEngine(cfg, params=params)
    dec.start()
    try:
        rids = []
        for i in range(5):
            state = prefill_only(pre, [i + 1] * 8, temperature=0.0)
            rids.append(dec.submit_prefilled(state, max_tokens=5))
        outs = [dec.result(r, timeout=120.0) for r in rids]
        assert all(o["error"] is None for o in outs)
        assert all(o["num_generated_tokens"] == 5 for o in outs)
        stats = dec.engine_stats()
        assert stats["active_slots"] == 0
        assert stats["free_pages"] == 31
    finally:
        dec.shutdown()


@pytest.fixture
def disagg_app(ray_start_module):
    from ray_tpu import serve
    from ray_tpu.serve.llm.disagg import build_disagg_openai_app

    app = build_disagg_openai_app(_tiny_cfg(), route_prefix="/v1",
                                  num_prefill=2, num_decode=1)
    serve.run(app, name="llm-disagg", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    yield f"http://127.0.0.1:{proxy.port}"
    serve.shutdown()


def test_disagg_openai_http_e2e(disagg_app):
    """End-to-end: distinct prefill replicas and a decode ingress serving
    OpenAI requests over HTTP (VERDICT r2 item 4's done-bar)."""
    def post(payload):
        req = urllib.request.Request(
            f"{disagg_app}/v1/completions",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    outs = [post({"prompt": f"hello {i}", "max_tokens": 4,
                  "temperature": 0.0}) for i in range(4)]
    for out in outs:
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] == 4
        assert out["ray_tpu"]["ttft_s"] is not None

    # chat route must NOT fall through to the plain completions path
    req = urllib.request.Request(
        f"{disagg_app}/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": "hi"}],
                         "max_tokens": 3}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        chat = json.loads(r.read())
    assert chat["choices"][0]["message"]["role"] == "assistant"

    with urllib.request.urlopen(f"{disagg_app}/v1/models", timeout=30) as r:
        models = json.loads(r.read())
    assert models["data"][0]["mode"] == "disagg"


@pytest.fixture
def disagg_dag_app(ray_start_module):
    from ray_tpu import serve
    from ray_tpu.serve.llm.disagg import build_disagg_openai_app

    app = build_disagg_openai_app(_tiny_cfg(), route_prefix="/v1",
                                  num_prefill=2, num_decode=1,
                                  use_pipeline=True)
    serve.run(app, name="llm-disagg-dag", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    yield f"http://{'127.0.0.1'}:{proxy.port}"
    serve.shutdown()


def test_disagg_dag_pipeline_e2e(disagg_dag_app):
    """The prefill→decode handoff re-expressed on the compiled pipeline
    (mutable-channel aDAG path, VERDICT r3 item 4): same OpenAI surface,
    KV blobs ride channel edges instead of object-plane task returns."""
    def post(payload):
        req = urllib.request.Request(
            f"{disagg_dag_app}/v1/completions",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    outs = [post({"prompt": f"hello {i}", "max_tokens": 4,
                  "temperature": 0.0}) for i in range(4)]
    for out in outs:
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] == 4


def test_handoff_channel_capacity_sizing():
    """ADVICE r4: the compiled-pipeline channel must fit the LARGEST KV
    handoff blob the config can produce (>1 page, model dtype), not the
    8 MiB default that only fit the tiny test config."""
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.config import LLMConfig
    from ray_tpu.serve.llm.disagg import _handoff_channel_capacity

    mc = llama.llama3_1b(max_seq_len=2048)
    cfg = LLMConfig(model_id="x", model_config=mc, page_size=128,
                    max_prompt_len=1024, max_seq_len=2048)
    cap = _handoff_channel_capacity(cfg)
    pages = -(-cfg.max_prompt_len // cfg.page_size)
    assert pages == 8  # a real multi-page prompt
    kv_bytes = 2 * mc.n_layers * mc.n_kv_heads * pages * cfg.page_size \
        * mc.head_dim * np.dtype(mc.dtype).itemsize
    assert cap > kv_bytes          # blob + framing headroom fits
    assert cap > 8 * 1024 * 1024   # and exceeds the old default
    # picklable envelope of that worst-case blob actually fits
    import pickle
    blob = {"kv_k": np.zeros((mc.n_layers, mc.n_kv_heads, pages,
                              cfg.page_size, mc.head_dim),
                             np.dtype(mc.dtype)),
            "kv_v": np.zeros((mc.n_layers, mc.n_kv_heads, pages,
                              cfg.page_size, mc.head_dim),
                             np.dtype(mc.dtype)),
            "prompt_tokens": list(range(cfg.max_prompt_len))}
    assert len(pickle.dumps(blob, protocol=5)) <= cap
