"""ray_tpu.dashboard — minimal cluster dashboard + log access.

TPU-native analog of the reference's dashboard head
(/root/reference/python/ray/dashboard/head.py + state_aggregator.py): an
aiohttp server exposing the state API as JSON plus a single-page HTML view.
No per-node agents — the control plane already aggregates everything, and
worker logs are read through `ray_tpu.util.state.worker_logs()`.

Endpoints:
    GET /              — HTML overview (auto-refreshing tables)
    GET /api/nodes     — node table
    GET /api/actors    — actor table
    GET /api/tasks     — recent task events
    GET /api/pgs       — placement groups
    GET /api/jobs      — submitted jobs
    GET /api/logs      — worker log files (?worker_id=&tail=)
"""

from ray_tpu.dashboard.app import Dashboard, start_dashboard

__all__ = ["Dashboard", "start_dashboard"]
