"""graftlint core: findings, pragmas, the pass registry, and the runner.

The reference runtime keeps its heavily threaded C++ core honest with
TSan/ASan wiring in the build; this package is the Python/JAX analog — a
pure-`ast` static analyzer for the bug classes this codebase has actually
shipped (see ISSUE 9): blocking I/O under locks, fire-and-forget RPC on
delivery-dependent paths, host syncs in engine hot paths, jit-boundary
drift, and unbounded handler-fed containers.

Design constraints, in order:

- **No imports of analyzed code.** Everything is `ast.parse` over file
  text — the tier-1 gate runs the full package in well under its 15 s
  budget, JAX-free, on any CPU box.
- **Low noise over high recall.** Every pass models *this* codebase's
  idioms (``with self._lock:``, ``RpcClient.notify``, ``_h_*`` handlers)
  and offers a per-site escape hatch: a ``# graftlint:`` pragma on the
  offending line (or the line above it, or the enclosing ``def``) plus a
  committed baseline with per-finding justifications.
- **Deterministic output.** Findings sort by (path, line, pass id);
  baseline keys omit line numbers so unrelated edits don't churn them.

Pragma syntax (comment anywhere on the relevant line)::

    # graftlint: fire-and-forget                 (alias for disable=rpc-ack)
    # graftlint: disable=lock-discipline
    # graftlint: disable=host-sync,jit-hygiene
    # graftlint: disable                          (all passes; avoid)
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Iterable, Optional

# tokens a pragma may carry; aliases map onto pass ids
_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*([A-Za-z0-9_,=\- ]+)")
_PRAGMA_ALIASES = {"fire-and-forget": "rpc-ack"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit.

    ``path`` is repo-relative; ``symbol`` is the enclosing qualname
    (``Class.method`` / ``function`` / ``<module>``); ``tag`` is a short
    stable token naming the offending operation — the baseline key is
    built from (pass_id, path, symbol, tag) so line drift from unrelated
    edits never invalidates a baselined entry.
    """

    pass_id: str
    path: str
    line: int
    symbol: str
    message: str
    hint: str
    tag: str

    @property
    def key(self) -> str:
        return f"{self.pass_id}::{self.path}::{self.symbol}::{self.tag}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_id}] "
                f"{self.symbol}: {self.message} (fix: {self.hint})")

    def to_dict(self) -> dict:
        return {"pass": self.pass_id, "file": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "hint": self.hint, "key": self.key}


class ModuleSource:
    """One parsed file: tree, raw lines, and the pragma map."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.tree = ast.parse(text, filename=path)
        # line -> set of disabled pass ids ("*" disables everything)
        self.pragmas: dict[int, set[str]] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            tags: set[str] = set()
            for raw in re.split(r"[,\s]+", m.group(1).strip()):
                if not raw:
                    continue
                if raw == "disable":
                    tags.add("*")
                elif raw.startswith("disable="):
                    tags.update(t for t in raw[len("disable="):].split(",")
                                if t)
                else:
                    tags.add(_PRAGMA_ALIASES.get(raw, raw))
            self.pragmas[i] = tags

    def suppressed(self, pass_id: str, *lines: int) -> bool:
        """True when any of ``lines`` (or the line just above the first)
        carries a pragma disabling ``pass_id``."""
        candidates = set(lines)
        if lines:
            candidates.add(lines[0] - 1)
        for ln in candidates:
            tags = self.pragmas.get(ln)
            if tags and ("*" in tags or pass_id in tags):
                return True
        return False


class Pass:
    """Base class: subclasses set ``id``/``title``/``hint`` and implement
    ``run``. ``scope`` controls membership in the default package sweep —
    "package" passes run over ``ray_tpu/``; "tests" passes (the tier-1
    mark guard) only run when explicitly requested."""

    id: str = ""
    title: str = ""
    hint: str = ""
    scope: str = "package"

    def run(self, module: ModuleSource) -> list[Finding]:
        raise NotImplementedError

    # -- helpers shared by every pass -----------------------------------
    def emit(self, module: ModuleSource, node: ast.AST, symbol: str,
             message: str, tag: str, hint: Optional[str] = None,
             extra_pragma_lines: Iterable[int] = ()) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        if module.suppressed(self.id, line, *extra_pragma_lines):
            return None
        return Finding(self.id, module.relpath, line, symbol, message,
                       hint if hint is not None else self.hint, tag)


_REGISTRY: dict[str, Pass] = {}


def register(pass_cls: type) -> type:
    """Class decorator: instantiate and add to the registry (import of a
    pass module is what makes its passes available)."""
    inst = pass_cls()
    if not inst.id:
        raise ValueError(f"{pass_cls.__name__} has no id")
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate pass id {inst.id!r}")
    _REGISTRY[inst.id] = inst
    return pass_cls


def all_passes() -> dict[str, Pass]:
    _load_builtin_passes()
    return dict(_REGISTRY)


def default_passes() -> list[Pass]:
    """The package-sweep set (everything except tests-scoped passes)."""
    return [p for p in all_passes().values() if p.scope == "package"]


_loaded = False


def _load_builtin_passes() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    # imports register via the @register decorator
    from ray_tpu.analysis import (passes_concurrency, passes_growth,  # noqa: F401
                                  passes_jax, passes_tests)


def qualname_of(stack: list[ast.AST]) -> str:
    parts = [n.name for n in stack
             if isinstance(n, (ast.ClassDef, ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    return ".".join(parts) if parts else "<module>"


def iter_functions(tree: ast.AST):
    """Yield (func_node, qualname, class_node_or_None) for every function
    in the module, including nested ones."""
    out = []

    def walk(node, stack, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, qualname_of(stack + [child]), cls))
                walk(child, stack + [child], cls)
            elif isinstance(child, ast.ClassDef):
                walk(child, stack + [child], child)
            else:
                walk(child, stack, cls)

    walk(tree, [], None)
    return out


def repo_root() -> str:
    """Parent directory of the ray_tpu package (the repo checkout)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_source_files(paths: Iterable[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.abspath(os.path.join(dirpath, fn)))
    return sorted(set(files))


def run_passes(paths: Optional[Iterable[str]] = None,
               passes: Optional[Iterable[Pass]] = None,
               rel_to: Optional[str] = None,
               on_error: Optional[Callable[[str, Exception], None]] = None,
               ) -> list[Finding]:
    """Run ``passes`` (default: the package set) over every ``.py`` file
    under ``paths`` (default: the installed ray_tpu package). Unparseable
    files are reported through ``on_error`` and skipped — the linter must
    not die on a half-written file."""
    if paths is None:
        paths = [package_dir()]
    if passes is None:
        passes = default_passes()
    else:
        passes = list(passes)
        _load_builtin_passes()
    if rel_to is None:
        rel_to = repo_root()
    findings: list[Finding] = []
    for path in iter_source_files(paths):
        try:
            text = open(path, encoding="utf-8").read()
            rel = os.path.relpath(path, rel_to)
            if rel.startswith(".."):
                rel = path
            module = ModuleSource(path, rel.replace(os.sep, "/"), text)
        except (OSError, SyntaxError, ValueError) as e:
            if on_error is not None:
                on_error(path, e)
            continue
        for p in passes:
            findings.extend(f for f in p.run(module) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.tag))
    return findings
