"""Compatibility re-export — the profiling helpers live in
``ray_tpu.observability.profiling`` (one home for local context-manager
helpers AND the remote-drivable capture subsystem; this module used to
carry a diverging copy of ``save_device_memory_profile``)."""

from ray_tpu.observability.profiling import (annotate, dump_thread_stacks,
                                             profile_step, profile_trace,
                                             save_device_memory_profile)

__all__ = ["annotate", "dump_thread_stacks", "profile_step",
           "profile_trace", "save_device_memory_profile"]
